"""BackupService — etcd snapshot backup/restore (SURVEY.md §3.5, §5.4):
accounts (S3/OSS/SFTP/local endpoints), per-cluster cron strategies with
retention, snapshot files, restore as inverse playbook."""

from __future__ import annotations

import re

from kubeoperator_tpu.adm import AdmContext, ClusterAdm, backup_phases, restore_phases
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import BackupAccount, BackupFile, BackupStrategy
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import NotFoundError, PhaseError, ValidationError
from kubeoperator_tpu.utils.ids import now_iso

# DNS-1123-ish: what velero/k8s accept for backup and namespace names; also
# exactly what keeps user input shell/ansible-argument-inert
_K8S_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]{0,251}[a-z0-9])?$")


def _check_k8s_name(value: str, what: str) -> None:
    # fullmatch: `$` alone would accept a trailing newline, letting the
    # recorded name diverge from what velero actually creates
    if not _K8S_NAME_RE.fullmatch(value):
        raise ValidationError(f"invalid {what} {value!r}")


class BackupService:
    def __init__(self, repos: Repositories, executor: Executor, events):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor)

    # ---- accounts ----
    def create_account(self, account: BackupAccount) -> BackupAccount:
        account.validate()
        return self.repos.backup_accounts.save(account)

    def list_accounts(self) -> list[BackupAccount]:
        return self.repos.backup_accounts.list()

    def delete_account(self, name: str) -> None:
        acct = self.repos.backup_accounts.get_by_name(name)
        self.repos.backup_accounts.delete(acct.id)

    # ---- strategies ----
    def set_strategy(self, cluster_name: str, account_name: str,
                     cron: str = "0 3 * * *", save_num: int = 7,
                     enabled: bool = True) -> BackupStrategy:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        account = self.repos.backup_accounts.get_by_name(account_name)
        existing = self.repos.backup_strategies.find(cluster_id=cluster.id)
        strategy = existing[0] if existing else BackupStrategy(cluster_id=cluster.id)
        strategy.account_id = account.id
        strategy.cron = cron
        strategy.save_num = save_num
        strategy.enabled = enabled
        strategy.validate()
        return self.repos.backup_strategies.save(strategy)

    def get_strategy(self, cluster_name: str) -> BackupStrategy | None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        found = self.repos.backup_strategies.find(cluster_id=cluster.id)
        return found[0] if found else None

    # ---- backup / restore ----
    def run_backup(self, cluster_name: str, account_name: str = "") -> BackupFile:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("etcd backup")
        if account_name:
            account = self.repos.backup_accounts.get_by_name(account_name)
        else:
            strategy = self.get_strategy(cluster_name)
            if strategy is None:
                raise ValidationError(
                    f"no backup account/strategy for {cluster_name}"
                )
            account = self.repos.backup_accounts.get(strategy.account_id)
        fname = f"etcd-{cluster.name}-{now_iso().replace(':', '')}.db"
        record = BackupFile(cluster_id=cluster.id, account_id=account.id,
                            name=fname)
        self.repos.backup_files.save(record)
        ctx = self._context(cluster, account, fname)
        try:
            self.adm.run(ctx, backup_phases())
        except PhaseError as e:
            record.status = "Failed"
            record.message = e.message
            self.repos.backup_files.save(record)
            self.events.emit(cluster.id, "Warning", "BackupFailed", e.message)
            raise
        record.status = "Uploaded"
        self.repos.backup_files.save(record)
        self._prune(cluster.id)
        self.events.emit(cluster.id, "Normal", "BackupDone",
                         f"etcd snapshot {fname} -> {account.name}")
        return record

    def restore(self, cluster_name: str, file_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("etcd restore")
        files = self.repos.backup_files.find(cluster_id=cluster.id,
                                             name=file_name)
        if not files:
            raise NotFoundError(kind="backup_file", name=file_name)
        record = files[0]
        account = self.repos.backup_accounts.get(record.account_id)
        ctx = self._context(cluster, account, file_name)
        try:
            self.adm.run(ctx, restore_phases())
        except PhaseError as e:
            self.events.emit(cluster.id, "Warning", "RestoreFailed", e.message)
            raise
        record.status = "Restored"
        self.repos.backup_files.save(record)
        self.events.emit(cluster.id, "Normal", "RestoreDone",
                         f"cluster restored from {file_name}")

    def list_files(self, cluster_name: str) -> list[BackupFile]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.backup_files.find(cluster_id=cluster.id)

    # ---- velero application backups (SURVEY.md §5.4(b)) ----
    def _require_velero(self, cluster) -> None:
        comps = self.repos.components.find(cluster_id=cluster.id,
                                           name="velero")
        if not comps or comps[0].status != "Installed":
            raise ValidationError(
                "velero component is not installed on this cluster"
            )

    def app_backup(self, cluster_name: str, backup_name: str = "",
                   namespaces: str = "") -> str:
        """`velero backup create` on a master; returns the backup name."""
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("application backup")
        self._require_velero(cluster)
        backup_name = backup_name or \
            f"app-{cluster.name}-{now_iso().replace(':', '').lower()}"
        _check_k8s_name(backup_name, "backup name")
        cmd = f"velero backup create {backup_name} --wait"
        if namespaces:
            for ns in namespaces.split(","):
                _check_k8s_name(ns, "namespace")
            cmd += f" --include-namespaces {namespaces}"
        self._velero_exec(cluster, cmd, "AppBackupFailed")
        self.events.emit(cluster.id, "Normal", "AppBackupDone",
                         f"velero backup {backup_name} created")
        return backup_name

    def app_restore(self, cluster_name: str, backup_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("application restore")
        self._require_velero(cluster)
        _check_k8s_name(backup_name, "backup name")
        self._velero_exec(
            cluster,
            f"velero restore create --from-backup {backup_name} --wait",
            "AppRestoreFailed",
        )
        self.events.emit(cluster.id, "Normal", "AppRestoreDone",
                         f"velero restore from {backup_name} completed")

    def _velero_exec(self, cluster, cmd: str, fail_reason: str) -> None:
        ctx = AdmContext.for_cluster(self.repos, cluster)
        task_id = self.adm.executor.run_adhoc(
            "command", cmd, ctx.inventory(), pattern="kube-master"
        )
        result = self.adm.executor.wait(task_id, timeout_s=1800)
        if not result.ok:
            self.events.emit(cluster.id, "Warning", fail_reason,
                             result.message)
            raise PhaseError("velero", result.message)

    # ---- internals ----
    def _context(self, cluster, account: BackupAccount, fname: str) -> AdmContext:
        strategy = self.repos.backup_strategies.find(cluster_id=cluster.id)
        save_num = strategy[0].save_num if strategy else 7
        return AdmContext.for_cluster(self.repos, cluster, None, {
            "backup_file_name": fname,
            "backup_account_type": account.type,
            "backup_bucket": account.bucket,
            # remote-side retention must track the strategy, or the endpoint
            # prunes snapshots the server still lists as restorable
            "backup_save_num": save_num,
            **{f"backup_{k}": v for k, v in account.vars.items()},
        })

    def _prune(self, cluster_id: str) -> None:
        strategy = self.repos.backup_strategies.find(cluster_id=cluster_id)
        keep = strategy[0].save_num if strategy else 7
        files = sorted(
            self.repos.backup_files.find(cluster_id=cluster_id),
            key=lambda f: f.created_at,
        )
        uploaded = [f for f in files if f.status == "Uploaded"]
        for record in uploaded[:-keep] if len(uploaded) > keep else []:
            self.repos.backup_files.delete(record.id)
