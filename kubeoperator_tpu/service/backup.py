"""BackupService — etcd snapshot backup/restore (SURVEY.md §3.5, §5.4):
accounts (S3/OSS/SFTP/local endpoints), per-cluster cron strategies with
retention, snapshot files, restore as inverse playbook."""

from __future__ import annotations

import re

from kubeoperator_tpu.adm import AdmContext, ClusterAdm, backup_phases, restore_phases
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import BackupAccount, BackupFile, BackupStrategy
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import NotFoundError, PhaseError, ValidationError
from kubeoperator_tpu.utils.ids import now_iso

# DNS-1123-ish: what velero/k8s accept for backup and namespace names; also
# exactly what keeps user input shell/ansible-argument-inert
_K8S_NAME_RE = re.compile(r"^[a-z0-9]([-a-z0-9.]{0,251}[a-z0-9])?$")


def _check_k8s_name(value: str, what: str) -> None:
    # fullmatch: `$` alone would accept a trailing newline, letting the
    # recorded name diverge from what velero actually creates
    if not _K8S_NAME_RE.fullmatch(value):
        raise ValidationError(f"invalid {what} {value!r}")


class BackupService:
    def __init__(self, repos: Repositories, executor: Executor, events,
                 retry_policy=None, retry_rng=None, journal=None,
                 scheduler=None):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng,
                              scheduler=scheduler)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    # ---- accounts ----
    def create_account(self, account: BackupAccount) -> BackupAccount:
        account.validate()
        return self.repos.backup_accounts.save(account)

    def list_accounts(self) -> list[BackupAccount]:
        return self.repos.backup_accounts.list()

    def delete_account(self, name: str) -> None:
        acct = self.repos.backup_accounts.get_by_name(name)
        self.repos.backup_accounts.delete(acct.id)

    def test_account(self, name: str, timeout_s: float = 5.0) -> dict:
        """Reachability probe for a backup endpoint — the 'test connection'
        button (VERDICT r2 #6): catch a bad endpoint at configure time, not
        when the 3am cron backup fails. Socket-level by design (works
        air-gapped, no cloud SDKs): S3/OSS endpoints must accept a TCP
        connect and answer HTTP; SFTP must present an SSH banner; local
        must be a writable directory. Updates the account's stored status.

        Never raises on a broken *configuration* (bad port string,
        unparseable endpoint, weird banner bytes) — a diagnostic that
        crashes on exactly the malformed input it exists to diagnose would
        be useless; everything maps to ok=False with the parse error."""
        import time as _time

        account = self.repos.backup_accounts.get_by_name(name)
        t0 = _time.perf_counter()
        try:
            result = self._probe_account(account, timeout_s)
        except (ValueError, TypeError, UnicodeError) as e:
            result = {"ok": False,
                      "message": f"account config invalid: {e}"}
        result["latency_ms"] = round((_time.perf_counter() - t0) * 1000, 1)
        result["type"] = account.type
        account.status = "Valid" if result["ok"] else "Invalid"
        self.repos.backup_accounts.save(account)
        return result

    def _probe_account(self, account: BackupAccount,
                       timeout_s: float) -> dict:
        import os as _os
        import urllib.parse

        if account.type == "local":
            path = str(account.vars.get("dir", "")).strip()
            if not path:
                return {"ok": False, "message": "local account has no dir"}
            if not _os.path.isdir(path):
                return {"ok": False, "message": f"{path} is not a directory"}
            if not _os.access(path, _os.W_OK):
                return {"ok": False, "message": f"{path} is not writable"}
            return {"ok": True, "message": f"{path} writable"}
        if account.type in ("s3", "oss"):
            endpoint = str(account.vars.get("endpoint", "")).strip()
            if not endpoint:
                return {"ok": False, "message": "account has no endpoint"}
            if "//" not in endpoint:
                endpoint = "https://" + endpoint
            url = urllib.parse.urlsplit(endpoint)
            port = url.port or (80 if url.scheme == "http" else 443)
            return self._probe_tcp(
                url.hostname or "", port, timeout_s,
                expect="http" if url.scheme == "http" else "tls",
            )
        if account.type == "sftp":
            host = str(account.vars.get("host", "")).strip()
            port = int(account.vars.get("port", 22) or 22)
            if not host:
                return {"ok": False, "message": "account has no host"}
            return self._probe_tcp(host, port, timeout_s, expect="ssh")
        # pragma: no cover - validate() forbids other types
        return {"ok": False, "message": f"untestable type {account.type}"}

    @staticmethod
    def _probe_tcp(host: str, port: int, timeout_s: float,
                   expect: str | None = None) -> dict:
        """TCP connect + protocol sniff: 'ssh' reads the server banner,
        'http' sends a minimal HEAD and wants an HTTP status line back,
        'tls' completes a TLS handshake (certificate NOT verified — this is
        a reachability probe, not an authenticity check)."""
        import socket
        import ssl

        try:
            with socket.create_connection((host, port), timeout=timeout_s) as s:
                s.settimeout(timeout_s)
                if expect == "ssh":
                    banner = s.recv(64)
                    if not banner.startswith(b"SSH-"):
                        return {
                            "ok": False,
                            "message": f"{host}:{port} answered but is not an "
                                       f"SSH server ({banner[:20]!r})",
                        }
                    proto = banner.split()[0].decode(errors="replace")
                    return {"ok": True, "message": f"{host}:{port} {proto}"}
                if expect == "http":
                    s.sendall(b"HEAD / HTTP/1.0\r\nHost: " +
                              host.encode(errors="replace") + b"\r\n\r\n")
                    status = s.recv(64)
                    if not status.startswith(b"HTTP/"):
                        return {
                            "ok": False,
                            "message": f"{host}:{port} answered but not HTTP "
                                       f"({status[:20]!r})",
                        }
                    line = status.split(b"\r")[0].decode(errors="replace")
                    return {"ok": True, "message": f"{host}:{port} {line}"}
                if expect == "tls":
                    ctx = ssl.create_default_context()
                    ctx.check_hostname = False
                    ctx.verify_mode = ssl.CERT_NONE
                    try:
                        with ctx.wrap_socket(s, server_hostname=host) as tls:
                            proto = tls.version() or "TLS"
                    except ssl.SSLError as e:
                        return {
                            "ok": False,
                            "message": f"{host}:{port} answered but TLS "
                                       f"handshake failed: {e}",
                        }
                    return {"ok": True,
                            "message": f"{host}:{port} {proto} handshake OK"}
                return {"ok": True, "message": f"{host}:{port} reachable"}
        except (OSError, socket.timeout) as e:
            return {"ok": False, "message": f"{host}:{port}: {e}"}

    # ---- strategies ----
    def set_strategy(self, cluster_name: str, account_name: str,
                     cron: str = "0 3 * * *", save_num: int = 7,
                     enabled: bool = True) -> BackupStrategy:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        account = self.repos.backup_accounts.get_by_name(account_name)
        existing = self.repos.backup_strategies.find(cluster_id=cluster.id)
        strategy = existing[0] if existing else BackupStrategy(cluster_id=cluster.id)
        strategy.account_id = account.id
        strategy.cron = cron
        strategy.save_num = save_num
        strategy.enabled = enabled
        strategy.validate()
        return self.repos.backup_strategies.save(strategy)

    def get_strategy(self, cluster_name: str) -> BackupStrategy | None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        found = self.repos.backup_strategies.find(cluster_id=cluster.id)
        return found[0] if found else None

    # ---- backup / restore ----
    def run_backup(self, cluster_name: str, account_name: str = "") -> BackupFile:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("etcd backup")
        if account_name:
            account = self.repos.backup_accounts.get_by_name(account_name)
        else:
            strategy = self.get_strategy(cluster_name)
            if strategy is None:
                raise ValidationError(
                    f"no backup account/strategy for {cluster_name}"
                )
            account = self.repos.backup_accounts.get(strategy.account_id)
        fname = f"etcd-{cluster.name}-{now_iso().replace(':', '')}.db"
        # every backup taken by this version embeds the sentinel (the
        # backup role writes it before snapshotting) — recorded on the
        # file row so restore knows whether to demand it back
        record = BackupFile(cluster_id=cluster.id, account_id=account.id,
                            name=fname, has_sentinel=True)
        self.repos.backup_files.save(record)
        ctx = self._context(cluster, account, fname)
        op = self.journal.open(cluster, "backup", vars={"file": fname})
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, backup_phases())
        except PhaseError as e:
            record.status = "Failed"
            record.message = e.message
            self.repos.backup_files.save(record)
            self.journal.close(op, ok=False, message=e.message)
            self.events.emit(cluster.id, "Warning", "BackupFailed", e.message)
            raise
        record.status = "Uploaded"
        self.repos.backup_files.save(record)
        self.journal.close(op, ok=True)
        self._prune(cluster.id)
        self.events.emit(cluster.id, "Normal", "BackupDone",
                         f"etcd snapshot {fname} -> {account.name}")
        return record

    def restore(self, cluster_name: str, file_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("etcd restore")
        files = self.repos.backup_files.find(cluster_id=cluster.id,
                                             name=file_name)
        if not files:
            raise NotFoundError(kind="backup_file", name=file_name)
        record = files[0]
        account = self.repos.backup_accounts.get(record.account_id)
        ctx = self._context(cluster, account, file_name)
        op = self.journal.open(cluster, "restore", vars={"file": file_name})
        self.journal.attach(op, ctx)
        # legacy snapshots (taken before sentinel support) cannot contain
        # the sentinel key — restore_verify_post skips that one check for
        # them instead of condemning every old backup as unrestorable
        ctx.extra_vars["restore_expect_sentinel"] = record.has_sentinel
        try:
            self.adm.run(ctx, restore_phases())
        except PhaseError as e:
            self.journal.close(op, ok=False, message=e.message)
            self.events.emit(cluster.id, "Warning", "RestoreFailed", e.message)
            raise
        self.journal.close(op, ok=True)
        record.status = "Restored"
        self.repos.backup_files.save(record)
        self.events.emit(cluster.id, "Normal", "RestoreDone",
                         f"cluster restored from {file_name}")

    def list_files(self, cluster_name: str) -> list[BackupFile]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.backup_files.find(cluster_id=cluster.id)

    # ---- velero application backups (SURVEY.md §5.4(b)) ----
    def _require_velero(self, cluster) -> None:
        comps = self.repos.components.find(cluster_id=cluster.id,
                                           name="velero")
        if not comps or comps[0].status != "Installed":
            raise ValidationError(
                "velero component is not installed on this cluster"
            )

    def app_backup(self, cluster_name: str, backup_name: str = "",
                   namespaces: str = "") -> str:
        """`velero backup create` on a master; returns the backup name."""
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("application backup")
        self._require_velero(cluster)
        backup_name = backup_name or \
            f"app-{cluster.name}-{now_iso().replace(':', '').lower()}"
        _check_k8s_name(backup_name, "backup name")
        cmd = f"velero backup create {backup_name} --wait"
        if namespaces:
            for ns in namespaces.split(","):
                _check_k8s_name(ns, "namespace")
            cmd += f" --include-namespaces {namespaces}"
        self._velero_exec(cluster, cmd, "AppBackupFailed")
        self.events.emit(cluster.id, "Normal", "AppBackupDone",
                         f"velero backup {backup_name} created")
        return backup_name

    def app_restore(self, cluster_name: str, backup_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("application restore")
        self._require_velero(cluster)
        _check_k8s_name(backup_name, "backup name")
        self._velero_exec(
            cluster,
            f"velero restore create --from-backup {backup_name} --wait",
            "AppRestoreFailed",
        )
        self.events.emit(cluster.id, "Normal", "AppRestoreDone",
                         f"velero restore from {backup_name} completed")

    def _velero_exec(self, cluster, cmd: str, fail_reason: str) -> None:
        ctx = AdmContext.for_cluster(self.repos, cluster)
        task_id = self.adm.executor.run_adhoc(
            "command", cmd, ctx.inventory(), pattern="kube-master"
        )
        result = self.adm.executor.wait(task_id, timeout_s=1800)
        if not result.ok:
            self.events.emit(cluster.id, "Warning", fail_reason,
                             result.message)
            raise PhaseError("velero", result.message)

    # ---- internals ----
    def _context(self, cluster, account: BackupAccount, fname: str) -> AdmContext:
        strategy = self.repos.backup_strategies.find(cluster_id=cluster.id)
        save_num = strategy[0].save_num if strategy else 7
        return AdmContext.for_cluster(self.repos, cluster, None, {
            "backup_file_name": fname,
            "backup_account_type": account.type,
            "backup_bucket": account.bucket,
            # remote-side retention must track the strategy, or the endpoint
            # prunes snapshots the server still lists as restorable
            "backup_save_num": save_num,
            **{f"backup_{k}": v for k, v in account.vars.items()},
        })

    def _prune(self, cluster_id: str) -> None:
        strategy = self.repos.backup_strategies.find(cluster_id=cluster_id)
        keep = strategy[0].save_num if strategy else 7
        files = sorted(
            self.repos.backup_files.find(cluster_id=cluster_id),
            key=lambda f: f.created_at,
        )
        uploaded = [f for f in files if f.status == "Uploaded"]
        for record in uploaded[:-keep] if len(uploaded) > keep else []:
            self.repos.backup_files.delete(record.id)
