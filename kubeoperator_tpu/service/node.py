"""NodeService — scale up/down (SURVEY.md §3.3).

Scale-up: [plan mode] terraform re-apply with count+N → new Hosts → join
phases limited to the new nodes. Scale-down: drain + remove + [plan mode]
terraform shrink. TPU plans scale in whole slices (num_slices±1) — chips
inside a slice are indivisible, a TPU-first rule with no GPU analog.
"""

from __future__ import annotations

from kubeoperator_tpu.adm import AdmContext, ClusterAdm, scale_down_phases, scale_up_phases
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import Node, NodeRole
from kubeoperator_tpu.models.cluster import ClusterPhaseStatus
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import NotFoundError, PhaseError, ValidationError
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.node")


class NodeService:
    def __init__(self, repos: Repositories, executor: Executor, provisioner,
                 events, retry_policy=None, retry_rng=None, journal=None,
                 scheduler=None):
        self.repos = repos
        self.executor = executor
        self.provisioner = provisioner
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng,
                              scheduler=scheduler)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    def list(self, cluster_name: str) -> list[Node]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.nodes.find(cluster_id=cluster.id)

    def scale_up(self, cluster_name: str, host_names: list[str]) -> list[Node]:
        """Manual-mode scale-up: join registered hosts as workers."""
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("node scale-up")
        if cluster.spec.tpu_enabled:
            raise ValidationError(
                "TPU clusters scale in whole slices via their plan "
                "(num_slices), not per-host"
            )
        if not host_names:
            raise ValidationError("scale_up requires host names")
        new_nodes: list[Node] = []
        for hname in host_names:
            host = self.repos.hosts.get_by_name(hname)
            if host.cluster_id and host.cluster_id != cluster.id:
                raise ValidationError(f"host {hname} already belongs to a cluster")
            host.cluster_id = cluster.id
            self.repos.hosts.save(host)
            node = Node(name=host.name, cluster_id=cluster.id, host_id=host.id,
                        role=NodeRole.WORKER.value, status="Joining")
            self.repos.nodes.save(node)
            new_nodes.append(node)

        # ctx before open: no fallible call between the journal/phase flip
        # and the try that guarantees a close
        ctx = self._context(cluster)
        ctx.new_node_names = {n.name for n in new_nodes}
        op = self.journal.open(cluster, "node-scale-up",
                               phase=ClusterPhaseStatus.SCALING,
                               vars={"hosts": list(host_names)})
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, scale_up_phases())
        except PhaseError as e:
            for node in new_nodes:
                node.status = "Failed"
                self.repos.nodes.save(node)
            cluster.status.phase = ClusterPhaseStatus.FAILED.value
            self.repos.clusters.save(cluster)
            self.journal.close(op, ok=False, message=e.message)
            raise
        for node in new_nodes:
            node.status = "Ready"
            self.repos.nodes.save(node)
        cluster.status.phase = ClusterPhaseStatus.READY.value
        self.repos.clusters.save(cluster)
        self.journal.close(op, ok=True)
        self.events.emit(cluster.id, "Normal", "NodesJoined",
                         f"{len(new_nodes)} workers joined {cluster_name}")
        return new_nodes

    def scale_down(self, cluster_name: str, node_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("node scale-down")
        nodes = self.repos.nodes.find(cluster_id=cluster.id, name=node_name)
        if not nodes:
            raise NotFoundError(kind="node", name=node_name)
        node = nodes[0]
        if node.role == NodeRole.MASTER.value:
            raise ValidationError("cannot remove a master node")
        workers = [
            n for n in self.repos.nodes.find(cluster_id=cluster.id)
            if n.role == NodeRole.WORKER.value
        ]
        if len(workers) <= 1:
            raise ValidationError("cannot remove the last worker")

        ctx = self._context(cluster)
        ctx.extra_vars["leaving_node"] = node.name
        op = self.journal.open(cluster, "node-scale-down",
                               phase=ClusterPhaseStatus.SCALING,
                               vars={"node": node_name})
        self.journal.attach(op, ctx)
        node.status = "Draining"
        self.repos.nodes.save(node)
        try:
            self.adm.run(ctx, scale_down_phases())
        except PhaseError as e:
            node.status = "Failed"
            self.repos.nodes.save(node)
            cluster.status.phase = ClusterPhaseStatus.FAILED.value
            self.repos.clusters.save(cluster)
            self.journal.close(op, ok=False, message=e.message)
            raise
        host = self.repos.hosts.get(node.host_id)
        host.cluster_id = ""
        self.repos.hosts.save(host)
        self.repos.nodes.delete(node.id)
        cluster.status.phase = ClusterPhaseStatus.READY.value
        self.repos.clusters.save(cluster)
        self.journal.close(op, ok=True)
        self.events.emit(cluster.id, "Normal", "NodeRemoved",
                         f"node {node_name} drained and removed")

    def _context(self, cluster) -> AdmContext:
        plan = (
            self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None
        )
        return AdmContext.for_cluster(self.repos, cluster, plan)
