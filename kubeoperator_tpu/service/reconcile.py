"""ReconcileService — the sweeps that make controller death routine
instead of an operator page.

Lifecycle operations run on threads inside the service container; a
`kill -9` (or OOM, or node loss) of the controller mid-create leaves the
cluster stranded in an in-flight phase (`Deploying`/`Scaling`/...) with no
thread behind it — before this PR, forever. The operation journal
(resilience/journal.py) records what was in flight; two sweeps turn that
record into recovery:

* **Boot sweep** (container start): when by construction no operation
  thread exists in THIS process, every open journal op this replica owns
  is an orphan:

  1. every open (`Running`) journal op is marked `Interrupted`, preserving
     the resume point (the cluster's first pending condition);
  2. every cluster in an in-flight phase flips to `Failed` with the resume
     point in its status message (pre-journal rows get a synthetic
     interrupted op, so the journal history is complete going forward);
  3. with `resilience.reconcile.auto_resume` on, interrupted operations
     whose resume path is safe re-enter automatically: create-shaped ops
     through `ClusterService.retry` (terraform re-apply reconciles the
     fleet, the phase engine re-enters at the first non-OK condition),
     terminations through `ClusterService.delete`, and fleet rollouts
     through `FleetService.resume` (their `vars` carry the waves).
     Everything else (upgrade, backup, day-2, components) stays Failed for
     the operator — those verbs need their original arguments and human
     judgment.

  Multi-controller posture (resilience/lease.py): an open op whose lease
  is live and held by a DIFFERENT controller is NOT an orphan — a peer
  replica is running it right now — so the boot sweep skips it.

* **Lease sweep** (`lease_sweep`, the cron lease tick): the failover half
  of the multi-controller contract. A lease whose holder stopped
  heartbeating past its TTL is dead-controller evidence; this replica
  CLAIMS the resource first (the CAS bumps the fencing epoch, so any
  zombie thread of the dead controller is rejected from here on), then
  interrupts the orphaned ops exactly like the boot sweep and auto-resumes
  them under the same knob. Our own expired leases are skipped — in this
  process the op thread may simply be slow, and the next heartbeat re-arms
  them; only a FOREIGN dead controller's work is taken over.
"""

from __future__ import annotations

from kubeoperator_tpu.models import OperationStatus
from kubeoperator_tpu.models.cluster import ClusterPhaseStatus, ConditionStatus
from kubeoperator_tpu.resilience import IN_FLIGHT_PHASES
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.reconcile")

# interrupted op kinds that re-enter safely through the existing resume
# paths: retry() for anything create-shaped, delete() for terminations
AUTO_RESUME_RETRY = frozenset({"create", "slice-scale", "reprovision",
                               "slice-replace"})
AUTO_RESUME_DELETE = frozenset({"terminate"})
# fleet rollouts resume through FleetService.resume: the op's own `vars`
# carry the remaining waves, so no original arguments are needed
AUTO_RESUME_FLEET = frozenset({"fleet-upgrade"})
# workload-train ops resume through WorkloadService.train(resume=True):
# the latest COMPLETE checkpoint carries the real step/optimizer state,
# so a controller death mid-train costs at most the steps since the last
# save — the resume opens a NEW op stitched into the original's trace
# (the old op's spans are not re-armed, unlike fleet reopen)
AUTO_RESUME_WORKLOAD = frozenset({"workload-train"})
# run kinds the QUEUE dispatches as lanes (train + serve): an orphan
# whose parent is a queue-entry op resumes through the queue path ONLY —
# a `workload-serve` op never standalone-auto-resumes either way
# (serving is stateless: the checkpoint IS its state; re-dispatch is
# the resume)
QUEUE_DISPATCHED_KINDS = frozenset({"workload-train", "workload-serve"})
# queue-entry ops re-enter through WorkloadQueueService.recover: the
# entry goes back to `pending` with its checkpoint (if a drain landed
# one) intact, the entry op is REOPENED (journal.reopen, the fleet
# contract — its spans are the tenant's whole stitched life), and the
# engine re-dispatches under normal gang/priority rules
AUTO_RESUME_QUEUE = frozenset({"workload-queued"})


def resume_point(cluster) -> str:
    """First pending OPERATION condition — the re-entry point a retry
    uses. The watchdog's `health` degradation marker is observability,
    not a phase: a Failed 'health' row must never masquerade as where an
    interrupted operation stopped."""
    from kubeoperator_tpu.service.watchdog import is_health_condition

    for cond in sorted(cluster.status.conditions,
                       key=lambda c: c.order_index):
        if is_health_condition(cond.name):
            continue
        if cond.status != ConditionStatus.OK.value:
            return cond.name
    return ""


class ReconcileService:
    def __init__(self, services) -> None:
        self.services = services

    # ---- shared per-op sweep ----
    def _sweep_one(self, op, cause: str) -> dict:
        """Interrupt ONE orphaned open op (fleet-scope or per-cluster),
        preserving its resume point; returns the sweep record. `cause`
        names who declared the owner dead ("controller restart" for the
        boot sweep, "controller <id> lease expired" for failover)."""
        repos = self.services.repos
        journal = self.services.clusters.journal
        if op.kind in AUTO_RESUME_FLEET or not op.cluster_id:
            # platform-scope op (fleet rollout, tenant workload): no
            # single cluster to strand. A fleet op's resumable state
            # (remaining waves, completed clusters) is already durable in
            # op.vars — the sweep just names the wave it died in; its
            # per-cluster child ops are swept like any other orphan. A
            # workload op resumes from its latest COMPLETE checkpoint
            # when one exists (real step/optimizer state, ISSUE 11);
            # without one, re-running the workload is the recovery.
            if op.kind in AUTO_RESUME_FLEET:
                wave = op.vars.get("current_wave", 0)
                resume = f"wave-{wave}"
                # the concurrent engine persists a per-cluster frontier
                # on the wave: name the lanes that were mid-upgrade
                in_flight = []
                for w in op.vars.get("waves", []):
                    if w.get("index") == wave:
                        in_flight = sorted(
                            (w.get("frontier") or {}).get("running", []))
                msg = (f"{cause}: fleet rollout was in flight "
                       f"(wave {wave}"
                       + (f"; {'+'.join(in_flight)} mid-upgrade"
                          if in_flight else "")
                       + "); `koctl fleet resume` continues "
                         "without re-running completed clusters")
            elif op.kind in AUTO_RESUME_QUEUE:
                state = (op.vars.get("entry") or {}).get("state", "?")
                ckpt = (op.vars.get("entry") or {}).get("checkpoint", "")
                resume = "queue"
                msg = (f"{cause}: queued workload was {state}; it "
                       f"re-enters the queue as pending"
                       + (f" and resumes from checkpoint {ckpt[:8]}"
                          if ckpt else "")
                       + " when the engine next dispatches")
            elif op.kind in QUEUE_DISPATCHED_KINDS \
                    and self._queue_dispatched(op):
                # a run the QUEUE dispatched: its entry op is being
                # re-queued by the AUTO_RESUME_QUEUE path above, and the
                # engine re-dispatches (resuming the entry's checkpoint)
                # under normal gang/priority rules — a second, un-queued
                # resume here would race it on the same devices
                resume = ""
                msg = (f"{cause}: queue-dispatched {op.kind} was in "
                       f"flight; its queue entry re-queues and resumes "
                       f"it — no standalone resume")
            elif op.kind in AUTO_RESUME_WORKLOAD:
                ckpt = self._workload_checkpoint(op)
                if ckpt is not None:
                    resume = f"checkpoint:{ckpt.id[:8]}"
                    msg = (f"{cause}: {op.kind} was in flight; "
                           f"checkpoint {ckpt.id[:8]} (step {ckpt.step}"
                           f"/{ckpt.target_steps}) is complete — "
                           f"`koctl workload train --resume --checkpoint "
                           f"{ckpt.id[:8]}` restores the real "
                           f"step/optimizer state")
                else:
                    resume = ""
                    msg = (f"{cause}: {op.kind} was in flight with no "
                           f"complete checkpoint; re-run the operation")
            elif op.kind == "workload-serve":
                # a standalone serving session holds no training state:
                # the checkpoint it restored from IS its state, so
                # re-submitting the server is the whole recovery
                resume = ""
                msg = (f"{cause}: serving session was in flight; the "
                       f"checkpoint is its state — re-submit to serve "
                       f"again")
            else:
                resume = ""
                msg = (f"{cause}: {op.kind} was in flight; re-run the "
                       f"operation (platform-scope ops do not resume)")
            journal.interrupt(op, resume_phase=resume, message=msg)
            return {
                "cluster": op.cluster_name, "op": op.id, "kind": op.kind,
                "resume_phase": op.resume_phase,
                "tenant": str((op.vars or {}).get("tenant", "") or ""),
            }
        cluster = None
        try:
            cluster = repos.clusters.get(op.cluster_id)
        except Exception:
            pass  # terminate op whose cluster row is already gone
        resume = resume_point(cluster) if cluster else ""
        # a concurrent (DAG) op also persisted its full launch frontier in
        # op.vars["frontier"] (journal.record_frontier): resume_phase stays
        # the compact first-pending-condition contract, the vars carry the
        # whole in-flight set — `koctl cluster operations --json` shows both
        frontier = (op.vars or {}).get("frontier") or {}
        in_flight = sorted(frontier.get("running", []))
        detail = (f"; DAG frontier was {'+'.join(in_flight)}"
                  if len(in_flight) > 1 else "")
        journal.interrupt(
            op, resume_phase=resume,
            message=f"{cause}: {op.kind} was in flight"
            + (f" (phase {op.phase})" if op.phase else "") + detail,
        )
        if cluster is not None:
            self._strand(cluster, op.resume_phase)
        return {
            "cluster": op.cluster_name, "op": op.id, "kind": op.kind,
            "resume_phase": op.resume_phase,
            "_cluster_id": cluster.id if cluster is not None else "",
        }

    def _queue_dispatched(self, op) -> bool:
        """Whether a workload op was dispatched by the queue (its parent
        is a queue-entry op) — those resume through the queue, never
        standalone."""
        from kubeoperator_tpu.service.queue import QUEUE_ENTRY_KIND

        if not op.parent_op_id:
            return False
        try:
            parent = self.services.repos.operations.get(op.parent_op_id)
        except Exception:
            return False
        return parent.kind == QUEUE_ENTRY_KIND

    def _workload_checkpoint(self, op):
        """The orphaned workload op's restorable state: its own newest
        complete checkpoint, else the newest complete one in the SAME
        tenant namespace (the op may have died before its first save
        while an earlier run's checkpoint still carries the tenant's
        state — but never another tenant's: the resume paths' isolation
        contract applies to the fallback too). None = nothing to resume
        from."""
        repos = self.services.repos
        tenant = str((op.vars or {}).get("tenant", "") or "")
        return (repos.checkpoints.latest_complete(op_id=op.id)
                or repos.checkpoints.latest_complete(tenant=tenant))

    # ---- boot sweep ----
    def boot_sweep(self) -> list[dict]:
        """Sweep orphans at container start; returns one record per
        reconciled cluster/op so callers (container boot log, tests) can
        see what happened."""
        cfg = self.services.config
        if not cfg.get("resilience.reconcile.enabled", True):
            return []
        repos = self.services.repos
        journal = self.services.clusters.journal
        leases = getattr(self.services, "leases", None)
        fencing = leases is not None and leases.enabled
        results: list[dict] = []
        claims: dict[str, int] = {}

        # 1. orphaned open ops — at boot, every open op is an orphan
        # UNLESS a live peer replica's lease says it is running elsewhere
        open_ops = repos.operations.find(
            status=OperationStatus.RUNNING.value)
        swept_clusters: set[str] = set()
        for op in open_ops:
            resource = op.cluster_id or op.id
            if fencing:
                holder = leases.holder(resource)
                if holder and holder.get("live") \
                        and holder["controller_id"] != leases.controller_id:
                    log.info(
                        "boot reconcile: op %s (%s) is leased by live "
                        "controller %s — not an orphan, skipping",
                        op.id, op.kind, holder["controller_id"])
                    continue
                claimed = leases.try_claim(resource)
                if claimed is None:
                    continue   # a peer won the resource between checks
                claims.setdefault(resource, int(claimed["epoch"]))
            record = self._sweep_one(op, "controller restart")
            record["_resource"] = resource
            cluster_id = record.pop("_cluster_id", "")
            if cluster_id:
                swept_clusters.add(cluster_id)
            results.append(record)

        # 2. in-flight clusters with NO open op (pre-journal rows, or a
        # journal write that never landed): synthesize the interrupted op
        # so the durable record still says what happened
        for phase in sorted(IN_FLIGHT_PHASES):
            for cluster in repos.clusters.find(phase=phase):
                if cluster.id in swept_clusters:
                    continue
                if fencing:
                    holder = leases.holder(cluster.id)
                    if holder and holder.get("live") \
                            and holder["controller_id"] \
                            != leases.controller_id:
                        continue   # a live peer owns this cluster
                    # claim BEFORE open (part 1's idiom): if a peer takes
                    # the cluster between the holder check and here, lose
                    # the race quietly — a raising claim inside open()
                    # would abort this replica's whole boot. Once this CAS
                    # wins, open()'s own claim is a same-controller
                    # renewal and cannot conflict.
                    if leases.try_claim(cluster.id) is None:
                        continue
                resume = resume_point(cluster)
                op = journal.open(cluster, "unknown")
                journal.interrupt(
                    op, resume_phase=resume,
                    message=f"controller restart: cluster found {phase} "
                            f"with no journaled operation",
                )
                self._strand(cluster, resume)
                swept_clusters.add(cluster.id)
                record = {
                    "cluster": cluster.name, "op": op.id, "kind": "unknown",
                    "resume_phase": resume,
                }
                if op.lease_epoch:
                    record["_resource"] = cluster.id
                    claims.setdefault(cluster.id, op.lease_epoch)
                results.append(record)

        if results:
            log.warning("boot reconcile: %d interrupted operation(s) swept",
                        len(results))
        self._resume_and_settle_claims(results, claims, leases)
        return results

    # ---- lease sweep (controller failover) ----
    def lease_sweep(self) -> list[dict]:
        """Take over the work of controllers that stopped heartbeating:
        claim each expired FOREIGN lease first (the CAS bumps the fencing
        epoch, so the dead controller's zombie threads are rejected from
        this instant on), then interrupt + optionally resume the orphaned
        ops behind it. Runs on the cron lease tick; also callable directly
        by drills/tests."""
        cfg = self.services.config
        leases = getattr(self.services, "leases", None)
        if leases is None or not leases.enabled:
            return []
        if not cfg.get("resilience.reconcile.enabled", True):
            return []
        repos = self.services.repos
        open_ops = repos.operations.find(
            status=OperationStatus.RUNNING.value)
        if not open_ops:
            return []
        by_resource: dict[str, list] = {}
        for op in open_ops:
            by_resource.setdefault(op.cluster_id or op.id, []).append(op)

        results: list[dict] = []
        claims: dict[str, int] = {}
        for row in leases.expired():
            resource = row["resource"]
            ops = by_resource.get(resource)
            if not ops:
                continue   # released/idle lease: nothing running behind it
            dead = row["controller_id"]
            if dead == leases.controller_id:
                # OUR lease expired while the op thread may still be alive
                # in this very process (stalled heartbeat, long GC): that
                # is not an orphan — the next heartbeat re-arms it. Only a
                # FOREIGN dead controller's work is taken over.
                continue
            claimed = leases.try_claim(resource)
            if claimed is None:
                continue   # the holder revived, or a peer won the takeover
            claims[resource] = int(claimed["epoch"])
            log.warning(
                "lease sweep: controller %s stopped heartbeating; "
                "re-claimed %s (epoch %d) with %d open op(s)",
                dead, resource, claimed["epoch"], len(ops))
            for op in ops:
                record = self._sweep_one(
                    op, f"controller {dead} lease expired")
                record.pop("_cluster_id", "")
                record["_resource"] = resource
                record["from_controller"] = dead
                results.append(record)
        self._resume_and_settle_claims(results, claims, leases)
        return results

    def _resume_and_settle_claims(self, results: list[dict],
                                  claims: dict[str, int], leases) -> None:
        """Auto-resume swept records (under the knob), then release the
        sweep's claims on resources nothing resumed on — a lease must mean
        'work is owned here', never dangle behind an op the operator still
        has to restart by hand. Resumed resources keep the claim: the
        resume path's journal.open renews it under the same epoch."""
        auto = self.services.config.get(
            "resilience.reconcile.auto_resume", False)
        resumed_resources: set[str] = set()
        for record in results:
            if auto:
                record["resumed"] = self._auto_resume(record)
                if record.get("resumed") and record.get("_resource"):
                    resumed_resources.add(record["_resource"])
        for resource, epoch in claims.items():
            if resource not in resumed_resources:
                leases.release(resource, epoch)
        for record in results:
            record.pop("_resource", None)

    def _strand(self, cluster, resume_phase: str) -> None:
        """Flip an orphaned in-flight cluster to Failed, resume point
        preserved — the same resting state a phase failure leaves, so every
        existing retry path applies unchanged."""
        # Initializing counts when an op was open: a crash in the window
        # between journal.open and the first phase flip must not leave a
        # forever-Initializing row either
        strandable = IN_FLIGHT_PHASES | {
            ClusterPhaseStatus.INITIALIZING.value}
        if cluster.status.phase not in strandable:
            # day-2/backup op died on a Ready cluster: the journal records
            # the interruption, the cluster needs no phase surgery
            return
        was = cluster.status.phase
        cluster.status.phase = ClusterPhaseStatus.FAILED.value
        cluster.status.message = (
            f"operation interrupted by controller restart (was {was})"
            + (f"; resume at phase {resume_phase!r}" if resume_phase else "")
        )
        self.services.repos.clusters.save(cluster)
        self.services.events.emit(
            cluster.id, "Warning", "OperationInterrupted",
            f"cluster {cluster.name}: {cluster.status.message}",
        )

    def _auto_resume(self, record: dict) -> bool:
        """Re-enter the existing resume path for one swept op (async — the
        container finishes booting while resumes run). Failures surface as
        events, never abort the boot."""
        name, kind = record["cluster"], record["kind"]
        try:
            if kind in AUTO_RESUME_FLEET:
                self.services.fleet.resume(record["op"], wait=False)
                log.info("auto-resumed fleet rollout %s after controller "
                         "restart", record["op"])
                return True
            if kind in AUTO_RESUME_QUEUE:
                requeued = self.services.workload_queue.recover(
                    op_id=record["op"], wait=False)
                if requeued:
                    log.info("re-queued workload entry for op %s after "
                             "controller restart", record["op"])
                return bool(requeued)
            if kind in AUTO_RESUME_WORKLOAD:
                resume_phase = record.get("resume_phase") or ""
                if not resume_phase.startswith("checkpoint:"):
                    return False   # no complete checkpoint: nothing to do
                ref = resume_phase.split(":", 1)[1]
                # async like every other resume verb: the sweep thread
                # also carries the lease heartbeat — blocking it behind
                # a compile+train could fence this very controller
                self.services.workloads.resume_from(
                    ref, tenant=record.get("tenant", ""), wait=False)
                log.info("auto-resuming workload %s from checkpoint %s "
                         "after controller restart", record["op"], ref)
                return True
            if kind in AUTO_RESUME_RETRY or (
                kind == "unknown"
                and self.services.clusters.get(name).plan_id
            ):
                self.services.clusters.retry(name, wait=False)
            elif kind in AUTO_RESUME_DELETE:
                self.services.clusters.delete(name, wait=False)
            else:
                return False
        except Exception as e:
            log.warning("auto-resume of %s on %s failed: %s", kind, name, e)
            try:
                cluster = self.services.repos.clusters.get_by_name(name)
                self.services.events.emit(
                    cluster.id, "Warning", "AutoResumeFailed",
                    f"{kind} on {name}: {e}")
            except Exception:
                pass
            return False
        log.info("auto-resumed %s on %s after controller restart",
                 kind, name)
        return True
