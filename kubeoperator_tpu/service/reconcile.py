"""ReconcileService — the boot-time sweep that makes controller death
routine instead of an operator page.

Lifecycle operations run on threads inside the service container; a
`kill -9` (or OOM, or node loss) of the controller mid-create leaves the
cluster stranded in an in-flight phase (`Deploying`/`Scaling`/...) with no
thread behind it — before this PR, forever. The operation journal
(resilience/journal.py) records what was in flight; this service runs at
container start (service/container.py), when by construction NO operation
thread can exist yet, so every open journal op and every in-flight cluster
is an orphan:

  1. every open (`Running`) journal op is marked `Interrupted`, preserving
     the resume point (the cluster's first pending condition);
  2. every cluster in an in-flight phase flips to `Failed` with the resume
     point in its status message (pre-journal rows get a synthetic
     interrupted op, so the journal history is complete going forward);
  3. with `resilience.reconcile.auto_resume` on, interrupted operations
     whose resume path is safe re-enter automatically: create-shaped ops
     through `ClusterService.retry` (terraform re-apply reconciles the
     fleet, the phase engine re-enters at the first non-OK condition) and
     terminations through `ClusterService.delete`. Everything else
     (upgrade, backup, day-2, components) stays Failed for the operator —
     those verbs need their original arguments and human judgment.
"""

from __future__ import annotations

from kubeoperator_tpu.models import OperationStatus
from kubeoperator_tpu.models.cluster import ClusterPhaseStatus, ConditionStatus
from kubeoperator_tpu.resilience import IN_FLIGHT_PHASES
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.reconcile")

# interrupted op kinds that re-enter safely through the existing resume
# paths: retry() for anything create-shaped, delete() for terminations
AUTO_RESUME_RETRY = frozenset({"create", "slice-scale", "reprovision"})
AUTO_RESUME_DELETE = frozenset({"terminate"})
# fleet rollouts resume through FleetService.resume: the op's own `vars`
# carry the remaining waves, so no original arguments are needed
AUTO_RESUME_FLEET = frozenset({"fleet-upgrade"})


def resume_point(cluster) -> str:
    """First pending OPERATION condition — the re-entry point a retry
    uses. The watchdog's `health` degradation marker is observability,
    not a phase: a Failed 'health' row must never masquerade as where an
    interrupted operation stopped."""
    from kubeoperator_tpu.service.watchdog import HEALTH_CONDITION

    for cond in sorted(cluster.status.conditions,
                       key=lambda c: c.order_index):
        if cond.name == HEALTH_CONDITION:
            continue
        if cond.status != ConditionStatus.OK.value:
            return cond.name
    return ""


class ReconcileService:
    def __init__(self, services) -> None:
        self.services = services

    def boot_sweep(self) -> list[dict]:
        """Sweep orphans; returns one record per reconciled cluster/op so
        callers (container boot log, tests) can see what happened."""
        cfg = self.services.config
        if not cfg.get("resilience.reconcile.enabled", True):
            return []
        repos = self.services.repos
        journal = self.services.clusters.journal
        results: list[dict] = []

        # 1. orphaned open ops — at boot, every open op is an orphan
        open_ops = repos.operations.find(
            status=OperationStatus.RUNNING.value)
        swept_clusters: set[str] = set()
        for op in open_ops:
            if op.kind in AUTO_RESUME_FLEET:
                # fleet op: no single cluster to strand; the resumable
                # state (remaining waves, completed clusters) is already
                # durable in op.vars — the sweep just names the wave it
                # died in. Its per-cluster child op is swept by this same
                # loop like any other orphan.
                wave = op.vars.get("current_wave", 0)
                journal.interrupt(
                    op, resume_phase=f"wave-{wave}",
                    message=f"controller restart: fleet rollout was in "
                            f"flight (wave {wave}); `koctl fleet resume` "
                            f"continues without re-running completed "
                            f"clusters",
                )
                results.append({
                    "cluster": op.cluster_name, "op": op.id,
                    "kind": op.kind, "resume_phase": op.resume_phase,
                })
                continue
            cluster = None
            try:
                cluster = repos.clusters.get(op.cluster_id)
            except Exception:
                pass  # terminate op whose cluster row is already gone
            resume = resume_point(cluster) if cluster else ""
            # a concurrent (DAG) op also persisted its full launch
            # frontier in op.vars["frontier"] (journal.record_frontier):
            # resume_phase stays the compact first-pending-condition
            # contract, the vars carry the whole in-flight set — `koctl
            # cluster operations --json` shows both
            frontier = (op.vars or {}).get("frontier") or {}
            in_flight = sorted(frontier.get("running", []))
            detail = (f"; DAG frontier was {'+'.join(in_flight)}"
                      if len(in_flight) > 1 else "")
            journal.interrupt(
                op, resume_phase=resume,
                message=f"controller restart: {op.kind} was in flight"
                + (f" (phase {op.phase})" if op.phase else "") + detail,
            )
            results.append({
                "cluster": op.cluster_name, "op": op.id, "kind": op.kind,
                "resume_phase": op.resume_phase,
            })
            if cluster is not None:
                swept_clusters.add(cluster.id)
                self._strand(cluster, op.resume_phase)

        # 2. in-flight clusters with NO open op (pre-journal rows, or a
        # journal write that never landed): synthesize the interrupted op
        # so the durable record still says what happened
        for phase in sorted(IN_FLIGHT_PHASES):
            for cluster in repos.clusters.find(phase=phase):
                if cluster.id in swept_clusters:
                    continue
                resume = resume_point(cluster)
                op = journal.open(cluster, "unknown")
                journal.interrupt(
                    op, resume_phase=resume,
                    message=f"controller restart: cluster found {phase} "
                            f"with no journaled operation",
                )
                self._strand(cluster, resume)
                swept_clusters.add(cluster.id)
                results.append({
                    "cluster": cluster.name, "op": op.id, "kind": "unknown",
                    "resume_phase": resume,
                })

        if results:
            log.warning("boot reconcile: %d interrupted operation(s) swept",
                        len(results))
        if cfg.get("resilience.reconcile.auto_resume", False):
            for record in results:
                record["resumed"] = self._auto_resume(record)
        return results

    def _strand(self, cluster, resume_phase: str) -> None:
        """Flip an orphaned in-flight cluster to Failed, resume point
        preserved — the same resting state a phase failure leaves, so every
        existing retry path applies unchanged."""
        # Initializing counts when an op was open: a crash in the window
        # between journal.open and the first phase flip must not leave a
        # forever-Initializing row either
        strandable = IN_FLIGHT_PHASES | {
            ClusterPhaseStatus.INITIALIZING.value}
        if cluster.status.phase not in strandable:
            # day-2/backup op died on a Ready cluster: the journal records
            # the interruption, the cluster needs no phase surgery
            return
        was = cluster.status.phase
        cluster.status.phase = ClusterPhaseStatus.FAILED.value
        cluster.status.message = (
            f"operation interrupted by controller restart (was {was})"
            + (f"; resume at phase {resume_phase!r}" if resume_phase else "")
        )
        self.services.repos.clusters.save(cluster)
        self.services.events.emit(
            cluster.id, "Warning", "OperationInterrupted",
            f"cluster {cluster.name}: {cluster.status.message}",
        )

    def _auto_resume(self, record: dict) -> bool:
        """Re-enter the existing resume path for one swept op (async — the
        container finishes booting while resumes run). Failures surface as
        events, never abort the boot."""
        name, kind = record["cluster"], record["kind"]
        try:
            if kind in AUTO_RESUME_FLEET:
                self.services.fleet.resume(record["op"], wait=False)
                log.info("auto-resumed fleet rollout %s after controller "
                         "restart", record["op"])
                return True
            if kind in AUTO_RESUME_RETRY or (
                kind == "unknown"
                and self.services.clusters.get(name).plan_id
            ):
                self.services.clusters.retry(name, wait=False)
            elif kind in AUTO_RESUME_DELETE:
                self.services.clusters.delete(name, wait=False)
            else:
                return False
        except Exception as e:
            log.warning("auto-resume of %s on %s failed: %s", kind, name, e)
            try:
                cluster = self.services.repos.clusters.get_by_name(name)
                self.services.events.emit(
                    cluster.id, "Warning", "AutoResumeFailed",
                    f"{kind} on {name}: {e}")
            except Exception:
                pass
            return False
        log.info("auto-resumed %s on %s after controller restart",
                 kind, name)
        return True
