"""UpgradeService — K8s version upgrade (SURVEY.md §3.4): one-minor-hop gate,
then adm upgrade phases (masters serial, workers rolling)."""

from __future__ import annotations

from kubeoperator_tpu.adm import AdmContext, ClusterAdm, upgrade_phases
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models.cluster import ClusterPhaseStatus
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import PhaseError, UpgradeError
from kubeoperator_tpu.version import SUPPORTED_K8S_VERSIONS


def _minor(version: str) -> int:
    try:
        return int(version.lstrip("v").split(".")[1])
    except (IndexError, ValueError):
        raise UpgradeError(message=f"unparseable k8s version {version!r}")


class UpgradeService:
    def __init__(self, repos: Repositories, executor: Executor, events,
                 retry_policy=None, retry_rng=None, journal=None):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    def validate_hop(self, current: str, target: str) -> None:
        if target not in SUPPORTED_K8S_VERSIONS:
            raise UpgradeError(
                message=f"{target} not in supported bundle "
                f"{SUPPORTED_K8S_VERSIONS}"
            )
        hop = _minor(target) - _minor(current)
        if hop < 1:
            raise UpgradeError(message=f"{target} is not newer than {current}")
        if hop > 1:
            raise UpgradeError(
                message=f"upgrades must move one minor at a time "
                f"({current} -> {target} is {hop})"
            )

    def upgrade(self, cluster_name: str, target_version: str):
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("upgrade")
        self.validate_hop(cluster.spec.k8s_version, target_version)
        # context built BEFORE the journal opens: nothing fallible may sit
        # between the op/phase flip and the close-guaranteeing try below,
        # or a plain exception strands an open op with a live controller
        ctx = AdmContext.for_cluster(
            self.repos, cluster,
            self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None,
            {"target_k8s_version": target_version},
        )
        # journal carries the target version, so an interrupted upgrade's
        # resume (re-issuing the same upgrade) needs no out-of-band memory
        op = self.journal.open(cluster, "upgrade",
                               phase=ClusterPhaseStatus.UPGRADING,
                               vars={"target_version": target_version})
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, upgrade_phases())
        except PhaseError as e:
            cluster.status.phase = ClusterPhaseStatus.FAILED.value
            cluster.status.message = e.message
            self.repos.clusters.save(cluster)
            self.journal.close(op, ok=False, message=e.message)
            self.events.emit(cluster.id, "Warning", "UpgradeFailed", e.message)
            raise
        cluster.spec.k8s_version = target_version
        cluster.status.phase = ClusterPhaseStatus.READY.value
        self.repos.clusters.save(cluster)
        self.journal.close(op, ok=True)
        self.events.emit(cluster.id, "Normal", "UpgradeDone",
                         f"{cluster_name} upgraded to {target_version}")
        return cluster
