"""UpgradeService — K8s version upgrade (SURVEY.md §3.4): one-minor-hop gate,
then adm upgrade phases (masters serial, workers rolling).

Fleet integration (docs/resilience.md "Fleet operations"): a rollout's
per-cluster upgrades pass `links` — the fleet op id plus a trace context
pointing at the wave span — so each child op journals under the fleet op
(migration 007) and its spans stitch into the rollout's single tree.
`rollback` is the fleet breaker's undo verb: the same upgrade phases run
back to the version the rollout recorded, with the verify attestation
checked against the ROLLBACK target — deliberately exempt from the
one-minor-hop direction gate, because undoing the hop just made is the
sanctioned downgrade.
"""

from __future__ import annotations

from kubeoperator_tpu.adm import AdmContext, ClusterAdm, upgrade_phases
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models.cluster import ClusterPhaseStatus
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import PhaseError, UpgradeError
from kubeoperator_tpu.version import SUPPORTED_K8S_VERSIONS


def _minor(version: str) -> int:
    try:
        return int(version.lstrip("v").split(".")[1])
    except (IndexError, ValueError):
        raise UpgradeError(message=f"unparseable k8s version {version!r}")


class UpgradeService:
    def __init__(self, repos: Repositories, executor: Executor, events,
                 retry_policy=None, retry_rng=None, journal=None,
                 scheduler=None):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng,
                              scheduler=scheduler)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    def validate_hop(self, current: str, target: str) -> None:
        if target not in SUPPORTED_K8S_VERSIONS:
            raise UpgradeError(
                message=f"{target} not in supported bundle "
                f"{SUPPORTED_K8S_VERSIONS}"
            )
        hop = _minor(target) - _minor(current)
        if hop < 1:
            raise UpgradeError(message=f"{target} is not newer than {current}")
        if hop > 1:
            raise UpgradeError(
                message=f"upgrades must move one minor at a time "
                f"({current} -> {target} is {hop})"
            )

    def upgrade(self, cluster_name: str, target_version: str,
                links: dict | None = None):
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("upgrade")
        self.validate_hop(cluster.spec.k8s_version, target_version)
        return self._run_version_phases(
            cluster, target_version, kind="upgrade",
            fail_reason="UpgradeFailed", done_reason="UpgradeDone",
            links=links)

    def rollback(self, cluster_name: str, to_version: str,
                 links: dict | None = None):
        """Fleet-wave undo: re-run the upgrade phases back to
        `to_version`. Only the bundle-membership half of the hop gate
        applies — direction is inverted by design, and the distance is
        bounded by construction (the rollout recorded the version this
        cluster ran one hop ago)."""
        if to_version not in SUPPORTED_K8S_VERSIONS:
            raise UpgradeError(
                message=f"{to_version} not in supported bundle "
                f"{SUPPORTED_K8S_VERSIONS}"
            )
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("rollback")
        if cluster.spec.k8s_version == to_version:
            return cluster   # nothing to undo (upgrade never landed)
        return self._run_version_phases(
            cluster, to_version, kind="rollback",
            fail_reason="RollbackFailed", done_reason="RolledBack",
            links=links)

    def _run_version_phases(self, cluster, target_version: str, *,
                            kind: str, fail_reason: str, done_reason: str,
                            links: dict | None):
        """The shared journaled phase run behind upgrade AND rollback —
        both move the cluster to `target_version` through the same adm
        phases; only the journal kind and event vocabulary differ."""
        links = links or {}
        # context built BEFORE the journal opens: nothing fallible may sit
        # between the op/phase flip and the close-guaranteeing try below,
        # or a plain exception strands an open op with a live controller
        ctx = AdmContext.for_cluster(
            self.repos, cluster,
            self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None,
            {"target_k8s_version": target_version},
        )
        # journal carries the target version, so an interrupted run's
        # resume (re-issuing the same verb) needs no out-of-band memory
        op = self.journal.open(cluster, kind,
                               phase=ClusterPhaseStatus.UPGRADING,
                               vars={"target_version": target_version},
                               trace=links.get("trace"),
                               parent_op_id=links.get("parent_op_id", ""))
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, upgrade_phases())
        except PhaseError as e:
            cluster.status.phase = ClusterPhaseStatus.FAILED.value
            cluster.status.message = e.message
            self.repos.clusters.save(cluster)
            self.journal.close(op, ok=False, message=e.message)
            self.events.emit(cluster.id, "Warning", fail_reason, e.message)
            raise
        cluster.spec.k8s_version = target_version
        cluster.status.phase = ClusterPhaseStatus.READY.value
        self.repos.clusters.save(cluster)
        self.journal.close(op, ok=True)
        self.events.emit(cluster.id, "Normal", done_reason,
                         f"{cluster.name} now at {target_version}")
        return cluster
