"""OverlaySettings — the shared runtime-settings discipline (SURVEY.md
§5.6: the reference keeps system settings in a DB table behind an admin
UI, app.yaml is only the bootstrap tier).

One flat settings document per consumer: reads merge
defaults <- app.yaml <- the stored overrides row; writes validate every
key against its default's TYPE and persist ONLY the submitted overrides
(persisting the merged doc would freeze config values — including
secrets — into the DB, and a later config rotation would silently lose).
Secret keys are masked on read, and a round-tripped mask means
"unchanged": keep the stored override if one exists, else drop the key so
app.yaml keeps supplying it.

NotifySettingsService predates this helper and keeps its own channelled
implementation (nested channels + per-name header merge don't fit a flat
document); new flat settings consumers (LDAP first) build on this one.
"""

from __future__ import annotations

import threading
from typing import Mapping

from kubeoperator_tpu.utils.errors import NotFoundError, ValidationError

MASK = "********"


class OverlaySettings:
    def __init__(self, repos, name: str, defaults: Mapping,
                 config_paths: Mapping[str, str], secret_keys: frozenset,
                 config) -> None:
        self.repos = repos
        self.name = name
        self.defaults = dict(defaults)
        self.config_paths = dict(config_paths)   # key -> app.yaml path
        self.secret_keys = frozenset(secret_keys)
        self.config = config
        # update() is read-modify-write over one row; concurrent admin
        # PUTs (thread-pool handlers) must not lose each other's overrides
        self._write_lock = threading.Lock()

    # ---- reads ----
    def _stored(self) -> dict:
        try:
            return dict(self.repos.settings.get_by_name(self.name).vars)
        except NotFoundError:
            # ONLY not-found means "no overrides yet" — a sick DB must
            # surface, not silently fall back to config
            return {}

    def effective(self) -> dict:
        out = dict(self.defaults)
        for key, path in self.config_paths.items():
            value = self.config.get(path, None)
            if value is not None:
                default = self.defaults[key]
                # config files are YAML-typed already; coerce the numeric
                # tiers the way the historical boot wiring did
                if isinstance(default, bool):
                    value = bool(value)
                elif isinstance(default, int):
                    value = int(value)
                elif isinstance(default, float):
                    value = float(value)
                out[key] = value
        for key, value in self._stored().items():
            if key in out:
                out[key] = value
        return out

    def get_public(self) -> dict:
        doc = self.effective()
        for key in self.secret_keys:
            if doc.get(key):
                doc[key] = MASK
        return doc

    # ---- writes ----
    def update(self, body: Mapping) -> dict:
        with self._write_lock:
            return self._update_locked(body)

    def _update_locked(self, body: Mapping) -> dict:
        from kubeoperator_tpu.models import Setting

        stored = self._stored()
        for key, value in dict(body).items():
            if key not in self.defaults:
                raise ValidationError(
                    f"unknown {self.name} setting {key!r}")
            default = self.defaults[key]
            if isinstance(default, bool):
                if not isinstance(value, bool):
                    raise ValidationError(
                        f"{self.name}.{key} must be a boolean, got {value!r}")
            elif isinstance(default, int):
                if not isinstance(value, int) or isinstance(value, bool):
                    raise ValidationError(
                        f"{self.name}.{key} must be an integer, got {value!r}")
            elif isinstance(default, float):
                if isinstance(value, bool) or \
                        not isinstance(value, (int, float)):
                    raise ValidationError(
                        f"{self.name}.{key} must be a number, got {value!r}")
            elif isinstance(default, str) and not isinstance(value, str):
                raise ValidationError(
                    f"{self.name}.{key} must be a string, got {value!r}")
            if key in self.secret_keys and value == MASK:
                continue   # mask means "unchanged"; config keeps supplying
            stored[key] = value
        self.validate_effective({**self.effective(), **stored})
        try:
            row = self.repos.settings.get_by_name(self.name)
        except NotFoundError:
            row = Setting(name=self.name)
        row.vars = stored
        self.repos.settings.save(row)
        return self.get_public()

    def validate_effective(self, merged: dict) -> None:
        """Subclass hook: cross-key checks over the would-be effective
        document (port ranges, URL schemes) — raise ValidationError."""
