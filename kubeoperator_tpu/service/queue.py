"""WorkloadQueueService — workloads as first-class queued tenants
(ISSUE 12 tentpole; docs/workloads.md "Queue and preemption").

`koctl workload submit` lands here: the request becomes a queue entry
(models/workload.py QueueEntry, migration 011) AND a platform-scope
journal operation (PR-9 `open_scoped`), so admission, placement,
preemption and completion are all provable from journal rows and one
stitched span tree — and lease fencing (PR 8), the boot reconciler, and
controller-death failover apply to queue state unchanged, because queue
state IS journal state.

The scheduler (workloads/queue.py holds the pure decisions) packs whole
gangs onto slice-pool capacity — an entry runs only when its ENTIRE
requested mesh fits — and implements priority preemption over the PR-11
drain protocol: a high-priority arrival that cannot fit picks the
lowest-priority capacity holder, `request_drain`s it (the victim
checkpoints the full TrainState at its next step boundary and closes
"drained"), takes the freed slices, and the victim re-enters the queue
and auto-resumes from its checkpoint when capacity returns. A victim
that never started (merely `placed`) is displaced back to pending with
no drain — it has no state to save.

Dispatch is CONCURRENT with per-run fault isolation (ISSUE 18): the
engine drives an `adm/pool.py BoundedPool` of `queue.max_concurrent`
lanes, so every placed gang launches as its own worker lane while ONE
coordinator owns every scheduling decision. `self._running` is the
per-entry run ledger (entry id → its op id), flipped together with the
persisted `running` state under the scheduler lock, so a preemption can
route a TARGETED drain at exactly one lane: two victims drain
concurrently and each checkpoints and re-queues independently, and a
chaos `ControllerDeath` on one lane lets siblings settle while the boot
reconciler recovers every lane to its recorded verdict. A submission
arriving mid-flight enqueues, runs a scheduling pass, and `kick`s the
coordinator so free lanes fill without waiting for the next settle.
`submit(wait=True)` still drives the engine on the caller's thread
(the CLI's synchronous posture) — with the default
`queue.max_concurrent = 1` the engine is bit-for-bit the old serial
cooperative loop.

The `serve` kind is the second workload verb (docs/workloads.md
"Serving"): a latency-class gang that restores a tenant checkpoint and
answers requests. Training is always preempted before serving
(workloads/queue.py choose_victims orders kinds), and a slice
preemption under a live server DEGRADES it onto the survivors
(`preempt_slice` → `request_degrade` → re-shard at reduced throughput)
instead of dropping the entry — drain is the fallback only when no
survivable layout exists.
"""

from __future__ import annotations

import re
import threading

from kubeoperator_tpu.models import (
    TERMINAL_STATES,
    OperationStatus,
    QueueEntry,
    priority_of,
)
from kubeoperator_tpu.utils.errors import (
    NotFoundError,
    ValidationError,
)
from kubeoperator_tpu.adm.pool import BoundedPool
from kubeoperator_tpu.observability import EventKind
from kubeoperator_tpu.utils.ids import now_ts
from kubeoperator_tpu.utils.logging import get_logger
from kubeoperator_tpu.utils.threads import spawn
from kubeoperator_tpu.workloads.queue import (
    SlicePoolView,
    SliceSlot,
    plan_aging,
    plan_schedule,
    slices_needed,
)

log = get_logger("service.queue")

QUEUE_ENTRY_KIND = "workload-queued"

# the accounting tenant the convergence controller's remediation entries
# ledger under (service/converge.py) — platform housekeeping, visibly
# separate from every real tenant in `koctl workload queue`
REMEDIATION_TENANT = "remediation"

_TENANT_RE = re.compile(r"^[a-z0-9][a-z0-9_-]{0,62}$")


def submit_kwargs(body: dict) -> dict:
    """The body→`WorkloadQueueService.submit` translation BOTH transports
    share (REST handler and `LocalClient._dispatch`) — the behavioral
    half of the KO-X010 parity contract, same pattern as
    `workload.train_kwargs`."""
    from kubeoperator_tpu.fleet.planner import optional_int

    wait = body.get("wait", False)
    if not isinstance(wait, bool):
        raise ValidationError("wait must be a boolean")
    slo = body.get("slo_ms")
    if slo is not None:
        try:
            slo = float(slo)
        except (TypeError, ValueError):
            raise ValidationError(
                f"slo_ms must be a number, got {slo!r}") from None
    return {
        "plan": str(body.get("plan", "") or ""),
        "mesh": str(body.get("mesh", "") or ""),
        "steps": optional_int("steps", body.get("steps")),
        "mode": str(body.get("mode", "") or ""),
        "priority": str(body.get("priority", "") or ""),
        "tenant": str(body.get("tenant", "") or ""),
        "kind": str(body.get("kind", "") or "train"),
        "requests": optional_int("requests", body.get("requests")),
        "slo_ms": slo,
        "wait": wait,
    }


class WorkloadQueueService:
    def __init__(self, services) -> None:
        self.s = services
        self.repos = services.repos
        self.journal = services.journal
        self.workloads = services.workloads
        cfg = services.config
        self.priority_default = str(
            cfg.get("queue.priority_default", "normal"))
        self.cfg_slices = int(cfg.get("queue.slices", 0))
        self.cfg_chips = int(cfg.get("queue.chips_per_slice", 0))
        self.preempt = bool(cfg.get("queue.preempt", True))
        self.max_entries = max(int(cfg.get("queue.max_entries", 64)), 1)
        self.aging_after_s = float(cfg.get("queue.aging_after_s", 0))
        self.max_concurrent = max(
            int(cfg.get("queue.max_concurrent", 1)), 1)
        # engine state, all guarded by _lock: one coordinator owns every
        # scheduling decision at a time; _running is the per-entry run
        # ledger (entry id → its op id, the dispatch key a targeted
        # drain/degrade routes at) so N concurrent lanes each stay
        # individually reachable
        self._lock = threading.RLock()
        self._engine_active = False
        self._running: dict[str, str] = {}
        self._pool: BoundedPool | None = None   # live only while driving
        self._lost_slices: set[str] = set()     # preempted out of the pool
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------ submit ----
    def submit(self, plan: str = "", mesh: str = "",
               steps: int | None = None, mode: str = "",
               priority: str = "", tenant: str = "", kind: str = "train",
               requests: int | None = None, slo_ms: float | None = None,
               wait: bool = True) -> dict:
        """Admit one workload into the queue as a journaled platform op
        and run a scheduling pass; with `wait`, drive the dispatch engine
        until the queue has no runnable work (the CLI's synchronous
        posture — the entry has usually reached a terminal state by
        return). Validation happens BEFORE the journal op opens, so a
        rejected submission leaves no strand."""
        import jax

        from kubeoperator_tpu.parallel.mesh import MeshSpec
        from kubeoperator_tpu.workloads.step import WORKLOAD_AXES

        kind = kind or "train"
        if kind not in ("train", "serve", "sweep"):
            raise ValidationError(
                f"queue entry kind {kind!r} not in "
                f"('train', 'serve', 'sweep')")
        if requests is not None and int(requests) < 1:
            raise ValidationError("workload serve needs requests >= 1")
        if slo_ms is not None and float(slo_ms) < 0:
            raise ValidationError("slo_ms must be >= 0")
        if kind != "serve" and (requests is not None
                                or slo_ms is not None):
            raise ValidationError(
                "requests/slo_ms are serving-tier knobs — only a "
                "kind='serve' entry takes them")
        priority = priority or (
            "scavenger" if kind == "sweep" else self.priority_default)
        rank = priority_of(priority)
        if kind == "sweep" and rank != priority_of("scavenger"):
            raise ValidationError(
                "workload sweep runs at the scavenger class — it must "
                "never displace a tenant workload")
        if tenant and not _TENANT_RE.match(tenant):
            raise ValidationError(
                f"tenant {tenant!r} must match {_TENANT_RE.pattern} "
                f"(it names a checkpoint directory)")
        counts = self.repos.workload_queue.counts_by_state()
        live = sum(n for state, n in counts.items()
                   if state not in TERMINAL_STATES)
        if live >= self.max_entries:
            raise ValidationError(
                f"queue is full ({live}/{self.max_entries} live "
                f"entries; queue.max_entries)")
        if plan:
            row = self.s.plans.get(plan)   # NotFoundError names the plan
            if not row.has_tpu():
                raise ValidationError(
                    f"plan {plan!r} has no TPU topology")
        ckpt_row = None
        if kind == "serve":
            # serving starts FROM a model: admission fails fast when the
            # tenant's index holds nothing complete to restore
            ckpt_row = self.repos.checkpoints.latest_complete(
                tenant=tenant or None)
            if ckpt_row is None:
                raise ValidationError(
                    "workload serve needs a COMPLETE checkpoint"
                    + (f" for tenant {tenant!r}" if tenant else "")
                    + " in the index — train first (serving restores a "
                    "model, it does not train one)")
        n_local = len(jax.devices())
        if kind == "sweep":
            devices = n_local          # the sweep wants the whole pool
            mesh = ""
        elif mesh:
            spec = MeshSpec.parse(mesh, axis_names=WORKLOAD_AXES,
                                  n_devices=n_local)
            devices = spec.total_devices
        elif ckpt_row is not None and ckpt_row.mesh:
            # an unpinned server sizes its gang from the checkpoint's
            # recorded mesh — the layout it will actually restore onto
            devices = 1
            for n in ckpt_row.mesh.values():
                devices *= int(n)
        else:
            devices = n_local
        if kind == "serve":
            steps = 0   # a server answers requests, it has no step count
        else:
            steps = int(steps) if steps is not None else int(
                self.s.config.get("workloads.steps", 4))
            if steps < 2:
                raise ValidationError("queued workloads need steps >= 2")

        op = self.journal.open_scoped(
            QUEUE_ENTRY_KIND,
            vars={"tenant": tenant} if tenant else None,
            message=(f"queued {kind} ({priority}"
                     + (f", tenant {tenant}" if tenant else "")
                     + f", {devices} device(s))"),
            scope="workload")
        entry = QueueEntry(
            op_id=op.id, tenant=tenant, kind=kind,
            priority_class=priority, priority=rank, plan=plan, mesh=mesh,
            steps=steps, mode=mode, devices=devices,
            requests=int(requests) if requests is not None else 0,
            slo_ms=float(slo_ms) if slo_ms is not None else 0.0)
        entry.validate()
        self.repos.workload_queue.save(entry)
        self._sync_op(entry, op=op, event=(
            EventKind.QUEUE_SUBMIT,
            f"{kind} submitted at {priority}",
            {"state": entry.state, "priority": priority,
             "workload": kind, "devices": devices}))
        log.info("workload %s queued: %s %s priority=%s tenant=%s "
                 "devices=%d", entry.id[:8], kind, mesh or "(default)",
                 priority, tenant or "-", devices)
        self.schedule()
        # the engine always gets a kick; `wait` only picks the caller's
        # thread (CLI) vs a background one (REST). When a loop is already
        # live — including THIS thread's own, for submissions made from a
        # running train's step hook — process() returns immediately and
        # the owning loop picks the entry up at its next boundary.
        self.process(wait=wait)
        return self.status(entry.id)

    def submit_remediation(self, cluster: str, action: str,
                           detail: str = "", priority: str = "",
                           payload: dict | None = None,
                           kick: bool = True,
                           wait: bool = False) -> dict:
        """Admit one convergence remediation as ledgered queue work
        (service/converge.py — the controller's ONLY write path into the
        fleet). Remediation entries are zero-slice gangs under the
        `remediation` tenant: they ride the queue for ordering, audit and
        the event stream, never for capacity — they cannot block, preempt
        or be preempted (workloads/queue.py). `kick=False` lets the
        controller batch a tick's submissions and drive the engine
        once."""
        priority = priority or "scavenger"
        rank = priority_of(priority)
        if action not in ("retry", "recover", "upgrade"):
            raise ValidationError(
                f"remediation action {action!r} not in "
                f"('retry', 'recover', 'upgrade')")
        counts = self.repos.workload_queue.counts_by_state()
        live = sum(n for state, n in counts.items()
                   if state not in TERMINAL_STATES)
        if live >= self.max_entries:
            raise ValidationError(
                f"queue is full ({live}/{self.max_entries} live "
                f"entries; queue.max_entries)")
        remediation = {"cluster": cluster, "action": action,
                       "detail": detail, **dict(payload or {})}
        op = self.journal.open_scoped(
            QUEUE_ENTRY_KIND,
            vars={"tenant": REMEDIATION_TENANT,
                  "remediation": remediation},
            message=f"remediation {action} for {cluster} ({priority})",
            scope="workload")
        entry = QueueEntry(
            op_id=op.id, tenant=REMEDIATION_TENANT, kind="remediation",
            priority_class=priority, priority=rank,
            steps=0, devices=0)
        entry.validate()
        self.repos.workload_queue.save(entry)
        self._sync_op(entry, op=op, event=(
            EventKind.QUEUE_SUBMIT,
            f"remediation {action} for {cluster} submitted at {priority}",
            {"state": entry.state, "priority": priority,
             "cluster": cluster, "action": action}))
        log.info("remediation %s queued: %s %s priority=%s",
                 entry.id[:8], action, cluster, priority)
        self.schedule()
        if kick:
            self.process(wait=wait)
        return self.status(entry.id)

    # ---------------------------------------------------------- capacity ----
    def pool_view(self) -> tuple[SlicePoolView, str]:
        """The schedulable slice pool: `queue.slices`/`chips_per_slice`
        when pinned, else every Ready TPU cluster's slices, else one
        virtual slice over the locally visible devices (the bare tier-1
        stack — a queue on an empty platform still runs workloads, like
        `workload train` always has)."""
        import jax

        slots: list[SliceSlot] = []
        source = "config"
        if self.cfg_slices > 0:
            chips = self.cfg_chips or max(
                len(jax.devices()) // self.cfg_slices, 1)
            slots = [SliceSlot(f"pool/{i}", chips)
                     for i in range(self.cfg_slices)]
        else:
            for cluster in self.repos.clusters.find(phase="Ready"):
                if not cluster.plan_id:
                    continue
                try:
                    plan = self.repos.plans.get(cluster.plan_id)
                    if not plan.has_tpu():
                        continue
                    topo = plan.topology()
                except Exception:
                    continue
                for i in range(topo.num_slices):
                    slots.append(SliceSlot(f"{cluster.name}/{i}",
                                           topo.chips))
            source = "clusters"
            if not slots:
                slots = [SliceSlot("local/0", len(jax.devices()))]
                source = "local"
        if self._lost_slices:
            # a preempted slice is OUT of the schedulable pool until
            # restore_slice returns it — nothing new places onto it
            slots = [s for s in slots
                     if s.slice_id not in self._lost_slices]
        view = SlicePoolView(slots=slots)
        for e in self.repos.workload_queue.active():
            if e.placement:
                view.holders[e.id] = list(e.placement)
        return view, source

    def capacity(self) -> dict:
        """The operator's capacity view (`koctl workload queue`
        header)."""
        view, source = self.pool_view()
        return {
            "slices": view.total,
            "chips_per_slice": view.chips_per_slice,
            "free": view.free_slices(),
            "held": {k: v for k, v in sorted(view.holders.items())},
            "lost": sorted(self._lost_slices),
            "source": source,
        }

    # ---------------------------------------------------------- schedule ----
    def schedule(self) -> dict:
        """One scheduling pass (pure decisions in workloads/queue.py):
        place whole gangs by priority, and — when the head pending entry
        is blocked — evict the cheapest strictly-lower-priority victim
        set: a drain for the victim that is physically running (the
        PR-11 checkpoint+drain protocol), a displacement for one that
        merely holds a reservation. Safe to call from any thread,
        including a running train's step hook (it mutates state only;
        dispatch belongs to the engine loop)."""
        with self._lock:
            self._apply_aging()
            pending = self.repos.workload_queue.pending()
            active = self.repos.workload_queue.active()
            view, _source = self.pool_view()
            decision = plan_schedule(pending, active, view,
                                     preempt=self.preempt)
            placed_ids = []
            for entry in pending:
                placement = decision.placements.get(entry.id)
                if placement is None:
                    continue
                entry.placement = list(placement)
                entry.slices_needed = len(placement)
                entry.state = "placed"
                self.repos.workload_queue.save(entry)
                self._sync_op(entry, event=(
                    EventKind.QUEUE_PLACE,
                    f"placed on {'+'.join(placement)}",
                    {"state": entry.state,
                     "placement": list(placement)}))
                placed_ids.append(entry.id)
            head = next((e for e in pending
                         if e.id not in decision.placements), None)
            for victim_id in decision.victims:
                self._evict(victim_id, by=head)
            return {"placed": placed_ids,
                    "victims": list(decision.victims)}

    def _apply_aging(self) -> None:
        """Priority aging (under _lock, via schedule): promote starved
        pending entries one class per elapsed `queue.aging_after_s`
        interval (pure decisions in workloads/queue.py plan_aging). The
        promotion is ledgered on the entry and mirrored into its journal
        op like every other scheduler-visible state change; created_at is
        untouched, so FIFO-within-class holds unchanged for everyone
        else."""
        if self.aging_after_s <= 0:
            return
        now = now_ts()
        for entry, promoted in plan_aging(
                self.repos.workload_queue.pending(), now,
                self.aging_after_s):
            was = entry.priority_class
            entry.priority_class = promoted
            entry.priority = priority_of(promoted)
            entry.aged_at = now
            entry.agings = list(entry.agings) + [{
                "from": was, "to": promoted, "at": now,
            }]
            self.repos.workload_queue.save(entry)
            self._sync_op(entry)
            log.info("queue entry %s aged %s -> %s after %.0fs pending",
                     entry.id[:8], was, promoted, now - entry.created_at)

    def _evict(self, victim_id: str, by) -> None:
        """Enact one eviction decision (under _lock, via schedule)."""
        try:
            victim = self.repos.workload_queue.get(victim_id)
        except NotFoundError:
            return
        by_id = by.id if by is not None else ""
        if victim.state == "running":
            if victim.preempted_by:
                return   # a drain is already in flight for it
            if victim.id not in self._running:
                # the lane is between states (or the row is a crash
                # strand the reconciler owns): marking preempted_by with
                # no drain to back it would block every later pass —
                # leave it, the next schedule pass retries
                return
            victim.preempted_by = by_id
            self.repos.workload_queue.save(victim)
            self._sync_op(victim, event=(
                EventKind.QUEUE_PREEMPT,
                f"preemption requested by {by_id[:8]}",
                {"state": victim.state, "by": by_id,
                 "mode": "drain"}))
            # TARGETED at this victim's lane: concurrent siblings keep
            # running — fault isolation is the whole point of the ledger
            self.workloads.request_drain(
                f"preempted by workload {by_id[:8]} "
                f"({by.priority_class})" if by is not None
                else "preempted",
                target=victim.op_id)
            return
        if victim.state == "placed":
            # never started: displace the reservation, nothing to drain
            victim.placement = []
            victim.state = "pending"
            victim.preemptions = list(victim.preemptions) + [{
                "kind": "displaced", "by": by_id, "at": now_ts(),
            }]
            self.repos.workload_queue.save(victim)
            self._sync_op(victim, event=(
                EventKind.QUEUE_PREEMPT,
                f"displaced by {by_id[:8]} before it started",
                {"state": victim.state, "by": by_id,
                 "mode": "displaced"}))
            log.info("workload %s displaced by %s before it started",
                     victim.id[:8], by_id[:8])

    # ------------------------------------------------------------ engine ----
    def process(self, wait: bool = True):
        """The dispatch engine: schedule, launch every placed gang onto
        the bounded lane pool (at most `queue.max_concurrent` physically
        live at once), fold each lane's verdict as it settles, repeat
        until nothing is runnable. Exactly one engine owns dispatch at a
        time; a second caller kicks the live coordinator (so its work is
        considered NOW, not at the next settle) and returns. `wait=False`
        runs the engine on a background thread (the REST submit path and
        the reconciler's recovery kick)."""
        if not wait:
            with self._lock:
                if self._engine_active:
                    t = None   # a live engine will pick the work up
                else:
                    t = spawn("workload-queue", self._process_guarded,
                              start=False)
                    self._threads.append(t)
            if t is None:
                self._kick()
                return None
            t.start()
            return None
        out = self._process_guarded()
        if isinstance(out, dict) and out.get("engine") == "busy":
            self._kick()
        return out

    def _kick(self) -> None:
        """Wake the live coordinator (if any) so it re-consults the
        scheduler immediately. Never called under `_lock` held by THIS
        frame's caller chain while also needed by the coordinator —
        the pool ref is snapshotted under `_lock`, the kick happens
        outside it (BoundedPool.kick's lock-ordering contract)."""
        with self._lock:
            pool = self._pool
        if pool is not None:
            pool.kick()

    def _process_guarded(self):
        from kubeoperator_tpu.resilience.lease import StaleEpochError

        with self._lock:
            if self._engine_active:
                return {"dispatched": 0, "engine": "busy"}
            self._engine_active = True
        dispatched = 0
        retired = False
        try:
            while True:
                dispatched += self._drive_pool()
                with self._lock:
                    # retire ATOMICALLY with the no-work check: a submit
                    # saves its row before consulting _engine_active, so
                    # either this check sees the row (loop again) or the
                    # submitter sees a retired engine (and becomes it) —
                    # a row can never fall between engines
                    self.schedule()
                    if not any(e.state == "placed"
                               for e in self.repos.workload_queue
                               .active()):
                        self._engine_active = False
                        retired = True
                        break
        except StaleEpochError as e:
            # fenced out mid-dispatch: a peer owns this queue state now —
            # stop cleanly, the new owner's engine continues the work
            log.warning("workload-queue engine fenced out: %s", e)
        finally:
            if not retired:
                with self._lock:
                    self._engine_active = False
        return {"dispatched": dispatched}

    def _drive_pool(self) -> int:
        """One BoundedPool run: the coordinator loop launches every
        placed gang (in priority order, capped at the free lanes),
        blocks until all lanes settle and the scheduler has nothing
        placed. `schedule` runs under `_lock` — the same lock `_evict`
        and `cancel` route targeted drains under — so the ledger flip to
        `running` and the persisted state flip are one atomic step per
        lane."""
        pool = BoundedPool(self.max_concurrent, "workload-queue")
        launched = {"n": 0}

        def schedule_cb(view):
            with self._lock:
                self.schedule()
                placed = [e for e in self.repos.workload_queue.active()
                          if e.state == "placed"]
                placed.sort(key=lambda e: (-e.priority, e.created_at,
                                           e.id))
                launches = placed[:view.free]
                for entry in launches:
                    self._mark_running(entry)
                launched["n"] += len(launches)
                return launches

        def settle_cb(entry, result, error):
            # _run_one folds its own outcome (including failures) into
            # queue state; an error surfacing HERE means the fold itself
            # broke — log it loudly, the reconciler owns the strand
            if error is not None:
                log.error("queue lane %s failed to settle: %s: %s",
                          entry.id[:8], type(error).__name__, error)

        with self._lock:
            self._pool = pool
        try:
            pool.run(schedule_cb, self._run_one, settle_cb)
        finally:
            with self._lock:
                self._pool = None
        return launched["n"]

    def _mark_running(self, entry: QueueEntry) -> None:
        """Flip one placed entry to `running` (under `_lock`, via the
        coordinator's schedule callback): the ledger entry and the
        persisted state flip TOGETHER, so a concurrent schedule() either
        sees `placed` (and may displace) or running-with-a-lane (and can
        route a targeted drain) — never a running row no drain can
        reach."""
        op = self.repos.operations.get(entry.op_id)
        if entry.started_at == 0.0:
            entry.started_at = now_ts()
            self.journal.record_windows(op, [{
                "name": "queue-wait", "start": entry.created_at,
                "end": entry.started_at,
                "attrs": {"priority": entry.priority_class,
                          "tenant": entry.tenant,
                          "slices": len(entry.placement)},
            }])
        self._running[entry.id] = entry.op_id
        entry.name = entry.id[:8]   # BoundedPool's lane-thread label
        entry.state = "running"
        self.repos.workload_queue.save(entry)
        self._sync_op(entry, op=op)

    def _run_one(self, entry: QueueEntry) -> None:
        """One lane's body (worker thread): dispatch the running entry
        through the existing WorkloadService seam and fold the outcome
        back into queue state. The run op stitches under the entry op
        (one trace per tenant workload life: queue-wait → run → drain →
        resume). A chaos BaseException (ControllerDeath) escapes the
        fold entirely — the entry stays `running` with a Running op, the
        exact strand boot recovery re-queues."""
        op = self.repos.operations.get(entry.op_id)
        if entry.kind == "remediation":
            self._run_remediation(entry)
            return
        trace = ({"trace_id": op.trace_id, "parent_span_id": op.id}
                 if op.trace_id else None)
        try:
            if entry.kind == "sweep":
                run_desc = self.workloads.sweep(
                    steps=entry.steps, tenant=entry.tenant,
                    trace=trace, parent_op_id=entry.op_id)
            elif entry.kind == "serve":
                # a (re-)dispatched server restores the tenant's latest
                # complete checkpoint — serving state IS the checkpoint,
                # so re-dispatch after a drain needs no resume math
                run_desc = self.workloads.serve(
                    mesh=entry.mesh, requests=entry.requests or None,
                    mode=entry.mode,
                    slo_ms=entry.slo_ms or None,
                    tenant=entry.tenant, trace=trace,
                    parent_op_id=entry.op_id)
            elif entry.checkpoint:
                # a previously-drained victim: restore its own checkpoint
                # and finish the remaining steps (train's resume math)
                run_desc = self.workloads.train(
                    resume=True, checkpoint=entry.checkpoint,
                    mesh=entry.mesh, mode=entry.mode,
                    tenant=entry.tenant, trace=trace,
                    parent_op_id=entry.op_id)
            else:
                run_desc = self.workloads.train(
                    plan=entry.plan, mesh=entry.mesh, steps=entry.steps,
                    mode=entry.mode, tenant=entry.tenant, trace=trace,
                    parent_op_id=entry.op_id)
        except Exception as e:
            entry = self.repos.workload_queue.get(entry.id)
            entry.placement = []
            entry.preempted_by = ""
            self._finish(entry, "failed", f"{type(e).__name__}: {e}")
            return
        finally:
            # off the ledger BEFORE folding: a drain can no longer reach
            # this lane, and a re-queued self must not race its own pop
            with self._lock:
                self._running.pop(entry.id, None)
        # reload: a scheduling pass during the run may have marked a
        # preemption (preempted_by) or a cancel on the row
        entry = self.repos.workload_queue.get(entry.id)
        entry.run_ops = list(entry.run_ops) + [run_desc["id"]]
        result = run_desc.get("result") or {}
        if run_desc.get("checkpoint"):
            entry.checkpoint = run_desc["checkpoint"]["id"]
        if result.get("drained"):
            self._handle_drained(entry, run_desc, result)
            return
        entry.preempted_by = ""
        entry.placement = []
        if run_desc["status"] == "Succeeded" and (
                result.get("ok") or entry.kind == "sweep"):
            self._finish(entry, "done", run_desc.get("message", ""))
        else:
            self._finish(entry, "failed",
                         run_desc.get("message", "run unhealthy"))

    def _run_remediation(self, entry: QueueEntry) -> None:
        """Dispatch one remediation entry through the convergence
        controller's execute seam (retry / recover / fleet-upgrade batch)
        and fold the verdict back into queue state. The entry op closes
        done/failed like any run; the converge tick's own attempt ledger
        and events are the controller's (service/converge.py)."""
        rem = dict(self.repos.operations.get(entry.op_id)
                   .vars.get("remediation") or {})
        converge = getattr(self.s, "converge", None)
        try:
            if converge is None:
                raise ValidationError(
                    "no convergence controller is wired for remediation "
                    "entries")
            result = converge.execute(rem)
            ok = bool(result.get("ok"))
            message = str(result.get("message", ""))
        except Exception as e:
            ok, message = False, f"{type(e).__name__}: {e}"
        finally:
            with self._lock:
                self._running.pop(entry.id, None)
        entry = self.repos.workload_queue.get(entry.id)
        entry.placement = []
        entry.preempted_by = ""
        self._finish(entry, "done" if ok else "failed", message)

    def _handle_drained(self, entry: QueueEntry, run_desc: dict,
                        result: dict) -> None:
        """A run that checkpoint+drained mid-flight: a preemption victim
        re-enters the queue (and auto-resumes from its checkpoint when
        capacity returns), a cancel target finishes `cancelled`. Either
        way the eviction is ledgered on the entry and as a span in the
        stitched trace."""
        op = self.repos.operations.get(entry.op_id)
        ckpt = (run_desc.get("checkpoint") or {}).get("id", "")
        record = {
            "kind": "drained",
            "by": entry.preempted_by,
            "reason": result.get("drain_reason", ""),
            "step": result.get("end_step"),
            "checkpoint": ckpt,
            "run_op": run_desc["id"],
            "at": now_ts(),
        }
        entry.preemptions = list(entry.preemptions) + [record]
        entry.checkpoint = ckpt or entry.checkpoint
        entry.placement = []
        entry.preempted_by = ""
        self.journal.record_windows(op, [{
            "name": "preempt", "start": now_ts(), "end": now_ts(),
            "attrs": {k: v for k, v in record.items()
                      if k not in ("at",) and v not in ("", None)},
        }])
        if entry.cancel_requested:
            self._finish(entry, "cancelled",
                         "cancelled by operator (drained at step "
                         f"{result.get('end_step')})")
            return
        entry.state = "drained"
        self.repos.workload_queue.save(entry)
        self._sync_op(entry, op=op, event=(
            EventKind.QUEUE_DRAIN,
            f"drained at step {result.get('end_step')}"
            + (f" (checkpoint {ckpt[:8]})" if ckpt else ""),
            {"state": entry.state, "step": result.get("end_step"),
             "by": record["by"], "checkpoint": ckpt}))
        # straight back into the queue: the checkpoint carries the state,
        # the scheduler re-places it when capacity returns
        entry.state = "pending"
        self.repos.workload_queue.save(entry)
        self._sync_op(entry, op=op, event=(
            EventKind.QUEUE_RESUME,
            "re-queued; resumes from its checkpoint when capacity "
            "returns",
            {"state": entry.state, "checkpoint": entry.checkpoint}))
        log.info("workload %s drained at step %s (checkpoint %s); "
                 "re-queued", entry.id[:8], result.get("end_step"),
                 ckpt[:8] if ckpt else "-")

    # ------------------------------------------------------------ cancel ----
    def cancel(self, ref: str) -> dict:
        """Cancel a queue entry: pending/placed entries finish
        `cancelled` immediately; a running entry gets the drain protocol
        (checkpoint at the next step boundary, THEN cancelled) so even a
        cancel never loses tenant state."""
        entry = self.resolve(ref)
        if entry.terminal:
            raise ValidationError(
                f"queue entry {entry.id[:8]} already finished "
                f"({entry.state})")
        with self._lock:
            if entry.state == "running" and entry.id in self._running:
                # a LIVE lane: drain it (targeted — concurrent siblings
                # keep running), the lane finishes the cancel when the
                # drained run returns
                entry.cancel_requested = True
                self.repos.workload_queue.save(entry)
                self._sync_op(entry)
                self.workloads.request_drain("cancelled by operator",
                                             target=entry.op_id)
                return self.describe(entry)
        # pending/placed — or a crash-stranded "running" row with no
        # lane behind it (its op is Interrupted): nothing is live,
        # finish the cancel directly
        entry.placement = []
        self._finish(entry, "cancelled", "cancelled by operator")
        # a freed reservation may unblock a waiting gang
        self._kick()
        return self.status(entry.id)

    # --------------------------------------------------- slice preemption ---
    def preempt_slice(self, slice_id: str) -> dict:
        """A slice is being taken (chaos drill / maintenance): pull it
        from the schedulable pool and settle every gang that holds it —
        degrade-not-die for servers, checkpoint+drain for training,
        displace for reservations that never started.

        * a RUNNING **serve** lane re-shards onto its surviving slices
          (`parallel.multislice.degraded_mesh_spec` → `request_degrade`
          → the server re-compiles at its next request boundary) and
          the entry STAYS running at reduced throughput — the queue
          never drops it;
        * a RUNNING **train** lane (or a server with no survivable
          layout) gets the targeted drain protocol: checkpoint at the
          next boundary, re-queue, auto-resume when capacity returns;
        * a merely **placed** holder is displaced back to pending.

        `restore_slice` returns the slice and kicks the engine."""
        actions: list[dict] = []
        with self._lock:
            if slice_id in self._lost_slices:
                return {"slice": slice_id, "actions": actions}
            self._lost_slices.add(slice_id)
            for entry in self.repos.workload_queue.active():
                if slice_id not in entry.placement:
                    continue
                if (entry.state == "running" and entry.kind == "serve"
                        and entry.id in self._running):
                    survivors = [s for s in entry.placement
                                 if s != slice_id]
                    spec = self._degraded_spec(
                        entry, len(entry.placement), len(survivors))
                    if survivors and spec is not None and \
                            self.workloads.request_degrade(
                                entry.op_id, spec):
                        entry.placement = survivors
                        entry.preemptions = list(entry.preemptions) + [{
                            "kind": "degraded", "slice": slice_id,
                            "survivors": list(survivors),
                            "at": now_ts(),
                        }]
                        self.repos.workload_queue.save(entry)
                        self._sync_op(entry, event=(
                            EventKind.QUEUE_DEGRADE,
                            f"slice {slice_id} preempted; serving "
                            f"degraded to {len(survivors)} slice(s) "
                            f"({spec})",
                            {"state": entry.state, "slice": slice_id,
                             "survivors": list(survivors),
                             "mesh": str(spec)}))
                        log.info(
                            "serve entry %s degraded to %d slice(s) "
                            "after %s preemption", entry.id[:8],
                            len(survivors), slice_id)
                        actions.append({"entry": entry.id,
                                        "action": "degraded",
                                        "survivors": len(survivors)})
                        continue
                if entry.state == "running":
                    if (entry.id in self._running
                            and not entry.preempted_by):
                        entry.preempted_by = f"slice:{slice_id}"
                        self.repos.workload_queue.save(entry)
                        self._sync_op(entry, event=(
                            EventKind.QUEUE_PREEMPT,
                            f"slice {slice_id} preempted under it; "
                            f"draining",
                            {"state": entry.state,
                             "by": entry.preempted_by,
                             "mode": "drain"}))
                        self.workloads.request_drain(
                            f"slice {slice_id} preempted",
                            target=entry.op_id)
                        actions.append({"entry": entry.id,
                                        "action": "drain"})
                    continue
                # placed, never started: displace the reservation
                entry.placement = []
                entry.state = "pending"
                entry.preemptions = list(entry.preemptions) + [{
                    "kind": "displaced", "by": f"slice:{slice_id}",
                    "at": now_ts(),
                }]
                self.repos.workload_queue.save(entry)
                self._sync_op(entry, event=(
                    EventKind.QUEUE_PREEMPT,
                    f"displaced by slice {slice_id} preemption",
                    {"state": entry.state, "by": f"slice:{slice_id}",
                     "mode": "displaced"}))
                actions.append({"entry": entry.id,
                                "action": "displaced"})
        self._kick()
        return {"slice": slice_id, "actions": actions}

    def restore_slice(self, slice_id: str, wait: bool = False) -> dict:
        """The preempted slice returns: put it back in the schedulable
        pool and (re)start the engine — drained victims re-place and
        resume from their checkpoints."""
        with self._lock:
            was_lost = slice_id in self._lost_slices
            self._lost_slices.discard(slice_id)
        if was_lost:
            self.schedule()
            self.process(wait=wait)
        return {"slice": slice_id, "restored": was_lost}

    def _degraded_spec(self, entry: QueueEntry, num_slices: int,
                       survivors: int):
        """The MeshSpec a degraded server re-shards onto, or None when
        the layout cannot shrink (single-slice gang, zero survivors, or
        only `tp` spans slices) — the caller falls back to the drain
        protocol."""
        if num_slices < 2 or survivors < 1:
            return None
        from kubeoperator_tpu.parallel.mesh import MeshSpec
        from kubeoperator_tpu.parallel.multislice import (
            degraded_mesh_spec,
        )
        from kubeoperator_tpu.workloads.step import WORKLOAD_AXES

        try:
            if entry.mesh:
                spec = MeshSpec.parse(
                    entry.mesh, axis_names=WORKLOAD_AXES,
                    n_devices=entry.devices or None)
                missing = tuple((a, 1) for a in WORKLOAD_AXES
                                if a not in spec.axis_names)
                if missing:
                    spec = MeshSpec(axes=spec.axes + missing)
            else:
                spec = MeshSpec(axes=(
                    ("data", entry.devices), ("fsdp", 1), ("tp", 1)))
            degraded, _axis = degraded_mesh_spec(
                spec, num_slices, lost=num_slices - survivors)
            return degraded
        except Exception as e:
            log.warning("serve entry %s cannot degrade (%s: %s); "
                        "falling back to drain", entry.id[:8],
                        type(e).__name__, e)
            return None

    # ---------------------------------------------------------- recovery ----
    def recover(self, op_id: str = "", wait: bool = False) -> list[str]:
        """Boot/lease-sweep recovery (service/reconcile.py): re-arm
        Interrupted queue-entry ops (`journal.reopen` — same resumable
        contract as fleet rollouts), put their entries back to pending
        (a previously-drained victim keeps its checkpoint and resumes
        from it), and kick the engine. Returns the re-queued entry
        ids."""
        ops = [o for o in self.repos.operations.find(
            kind=QUEUE_ENTRY_KIND,
            status=OperationStatus.INTERRUPTED.value)
            if not op_id or o.id == op_id]
        requeued: list[str] = []
        for op in ops:
            entry = self.repos.workload_queue.by_op(op.id)
            if entry is None or entry.terminal:
                continue
            self.journal.reopen(
                op, message="re-queued after controller restart")
            entry.state = "pending"
            entry.placement = []
            entry.preempted_by = ""
            self.repos.workload_queue.save(entry)
            self._sync_op(entry, op=op, event=(
                EventKind.QUEUE_RESUME,
                "re-queued after controller restart",
                {"state": entry.state, "checkpoint": entry.checkpoint}))
            requeued.append(entry.id)
            log.info("queue entry %s (%s) re-queued after interruption",
                     entry.id[:8], entry.kind)
        if requeued:
            self.process(wait=wait)
        return requeued

    def wait_all(self, timeout_s: float = 300.0) -> None:
        """Join background engine threads (container close)."""
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout_s)
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]

    # ----------------------------------------------------------- queries ----
    def resolve(self, ref: str) -> QueueEntry:
        """A queue entry by exact id or unique >=6-char prefix (the
        journal's op-ref resolution contract, applied to queue rows)."""
        if not ref:
            rows = self.repos.workload_queue.list()
            if not rows:
                raise NotFoundError(kind="queue entry", name="(latest)")
            return rows[-1]
        try:
            return self.repos.workload_queue.get(ref)
        except NotFoundError:
            pass
        rows = self.repos.workload_queue.list()
        matches = ([e for e in rows if e.id.startswith(ref)]
                   if len(ref) >= 6 else [])
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise ValidationError(
                f"queue entry ref {ref!r} is ambiguous "
                f"({len(matches)} matches)")
        raise NotFoundError(kind="queue entry", name=ref)

    def describe(self, entry: QueueEntry) -> dict:
        try:
            op_status = self.repos.operations.get(entry.op_id).status
        except NotFoundError:
            op_status = ""
        wait_s = (round(entry.started_at - entry.created_at, 3)
                  if entry.started_at else None)
        return {
            "id": entry.id,
            "op_id": entry.op_id,
            "op_status": op_status,
            "tenant": entry.tenant,
            "kind": entry.kind,
            "priority": entry.priority_class,
            "state": entry.state,
            "plan": entry.plan,
            "mesh": entry.mesh,
            "steps": entry.steps,
            "mode": entry.mode,
            "devices": entry.devices,
            "requests": entry.requests,
            "slo_ms": entry.slo_ms,
            "placement": list(entry.placement),
            "preemptions": list(entry.preemptions),
            "preempted_by": entry.preempted_by,
            "agings": list(entry.agings),
            "checkpoint": entry.checkpoint,
            "run_ops": list(entry.run_ops),
            "submitted_at": entry.created_at,
            "started_at": entry.started_at or None,
            "finished_at": entry.finished_at or None,
            "queue_wait_s": wait_s,
            "message": entry.message,
        }

    def entries(self) -> list[dict]:
        rows = self.repos.workload_queue.list()
        return [self.describe(e) for e in reversed(rows)]

    def status(self, ref: str = "") -> dict:
        return self.describe(self.resolve(ref))

    def queue_view(self) -> dict:
        """`koctl workload queue` / GET /api/v1/workloads/queue: the
        capacity header plus every entry, newest first."""
        return {"capacity": self.capacity(), "entries": self.entries()}

    # ----------------------------------------------------------- plumbing ---
    def _sync_op(self, entry: QueueEntry, op=None,
                 event: tuple | None = None) -> None:
        """Mirror the entry's scheduler-visible state into its journal
        op's vars — the durable half of the queue contract (fenced like
        every journal write, so a fenced-out scheduler cannot clobber a
        successor's queue state). `event` — an optional `(kind, message,
        payload)` bus event committing in the SAME fenced transaction as
        the vars save, so the event stream can never disagree with the
        durable queue state it narrates."""
        if op is None:
            op = self.repos.operations.get(entry.op_id)
        op.vars["entry"] = {
            "state": entry.state,
            "tenant": entry.tenant,
            "kind": entry.kind,
            "priority": entry.priority_class,
            "agings": list(entry.agings),
            "mesh": entry.mesh,
            "devices": entry.devices,
            "requests": entry.requests,
            "slo_ms": entry.slo_ms,
            "placement": list(entry.placement),
            "preemptions": list(entry.preemptions),
            "preempted_by": entry.preempted_by,
            "checkpoint": entry.checkpoint,
            "run_ops": list(entry.run_ops),
            "cancel_requested": entry.cancel_requested,
        }
        self.journal.save_vars(op, event=event)

    def _finish(self, entry: QueueEntry, state: str,
                message: str = "") -> None:
        entry.state = state
        entry.message = message
        entry.finished_at = now_ts()
        entry.cancel_requested = False
        self.repos.workload_queue.save(entry)
        op = self.repos.operations.get(entry.op_id)
        self._sync_op(entry, op=op, event=(
            EventKind.QUEUE_DONE, message or state,
            {"state": state}))
        if op.open:
            self.journal.close(op, ok=(state == "done"),
                               message=message or state)
        log.info("queue entry %s finished: %s (%s)", entry.id[:8], state,
                 message)
