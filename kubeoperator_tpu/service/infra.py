"""CRUD services for the IaaS-side schema: credentials, regions, zones,
plans, hosts (SURVEY.md §2.1 row 1b: region/zone/plan/host/credential
services)."""

from __future__ import annotations

from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import Credential, Host, Plan, Region, Zone
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import ConflictError, NotFoundError, ValidationError


class _Crud:
    """Shared create/list/get/delete with validation; subclasses pin repo."""

    kind = "entity"

    def __init__(self, repos: Repositories) -> None:
        self.repos = repos

    @property
    def repo(self):
        raise NotImplementedError

    def _pre_save(self, obj) -> None:
        pass

    def create(self, obj):
        obj.validate()
        self._pre_save(obj)
        try:
            self.repo.get_by_name(obj.name)
            raise ConflictError(kind=self.kind, name=obj.name)
        except NotFoundError:
            pass
        return self.repo.save(obj)

    def update(self, obj):
        obj.validate()
        self._pre_save(obj)
        self.repo.get(obj.id)  # must exist
        return self.repo.save(obj)

    def get(self, name: str):
        return self.repo.get_by_name(name)

    def list(self):
        return self.repo.list()

    def delete(self, name: str) -> None:
        self.repo.delete(self.repo.get_by_name(name).id)


class CredentialService(_Crud):
    kind = "credential"

    @property
    def repo(self):
        return self.repos.credentials

    def delete(self, name: str) -> None:
        cred = self.repo.get_by_name(name)
        used_by = [h for h in self.repos.hosts.list()
                   if h.credential_id == cred.id]
        if used_by:
            raise ValidationError(
                f"credential {name} is used by {len(used_by)} host(s) "
                f"(e.g. {used_by[0].name}); reassign them first"
            )
        self.repo.delete(cred.id)


class RegionService(_Crud):
    kind = "region"

    @property
    def repo(self):
        return self.repos.regions

    def _pre_save(self, region: Region) -> None:
        # the declared provider contract (provisioner/providers.py): a
        # typo'd key or missing credential must fail HERE, not render into
        # a terraform template's placeholder default at apply time
        from kubeoperator_tpu.provisioner.providers import (
            validate_region_vars,
        )

        validate_region_vars(region.provider, region.vars)

    def update(self, region: Region):
        # the read API masks secret vars per-key; a round-tripped mask
        # means "unchanged", not a new password of literal asterisks
        from kubeoperator_tpu.provisioner.providers import (
            secret_region_keys,
        )

        stored = self.repo.get(region.id)
        for key in secret_region_keys(region.provider):
            if region.vars.get(key) == "********":
                region.vars[key] = stored.vars.get(key, "")
        return super().update(region)

    def delete(self, name: str) -> None:
        region = self.repo.get_by_name(name)
        zones = self.repos.zones.find(region_id=region.id)
        if zones:
            raise ValidationError(
                f"region {name} still has {len(zones)} zone(s); "
                f"delete those first"
            )
        plans = [p for p in self.repos.plans.list()
                 if p.region_id == region.id]
        if plans:
            raise ValidationError(
                f"region {name} is referenced by plan {plans[0].name}"
            )
        self.repo.delete(region.id)


class ZoneService(_Crud):
    kind = "zone"

    @property
    def repo(self):
        return self.repos.zones

    def _pre_save(self, zone: Zone) -> None:
        region = self.repos.regions.get(zone.region_id)  # must exist
        from kubeoperator_tpu.provisioner.providers import (
            validate_zone_vars,
        )

        validate_zone_vars(region.provider, zone.vars)

    def list_for_region(self, region_name: str) -> list[Zone]:
        region = self.repos.regions.get_by_name(region_name)
        return self.repos.zones.find(region_id=region.id)

    def delete(self, name: str) -> None:
        zone = self.repo.get_by_name(name)
        plans = [p for p in self.repos.plans.list()
                 if zone.id in (p.zone_ids or [])]
        if plans:
            raise ValidationError(
                f"zone {name} is referenced by plan {plans[0].name}"
            )
        hosts = [h for h in self.repos.hosts.list()
                 if h.zone_id == zone.id]
        if hosts:
            raise ValidationError(
                f"zone {name} still carries {len(hosts)} host(s)"
            )
        self.repo.delete(zone.id)


class PlanService(_Crud):
    kind = "plan"

    @property
    def repo(self):
        return self.repos.plans

    def _pre_save(self, plan: Plan) -> None:
        if plan.region_id:
            self.repos.regions.get(plan.region_id)
        for zid in plan.zone_ids:
            self.repos.zones.get(zid)
        # TPU plans: worker_count 0 means derive; normalize at save so the
        # UI/API always see the real host count
        if plan.has_tpu() and plan.worker_count == 0:
            plan.worker_count = plan.topology().total_hosts

    def create(self, plan: Plan):
        # RFC1123 enforced on NEW names only (plan names become TPU-VM
        # instance prefixes + K8s object names); legacy rows persisted
        # under the old rules are grandfathered on update-in-place
        from kubeoperator_tpu.models.base import validate_dns_label

        validate_dns_label(plan.name, "plan name")
        return super().create(plan)

    def update(self, plan: Plan):
        from kubeoperator_tpu.models.base import validate_dns_label

        existing = self.repo.get(plan.id)
        if plan.name != existing.name:   # rename = a new name: full gate
            validate_dns_label(plan.name, "plan name")
        return super().update(plan)

    def delete(self, name: str) -> None:
        plan = self.repo.get_by_name(name)
        clusters = [c for c in self.repos.clusters.list()
                    if c.plan_id == plan.id]
        if clusters:
            raise ValidationError(
                f"plan {name} is used by cluster {clusters[0].name}; "
                f"delete the cluster first"
            )
        self.repo.delete(plan.id)

    def clone(self, name: str, new_name: str) -> Plan:
        """Copy a plan under a new name (the affordance the slice-scaling
        shared-plan guard points at: clusters needing independent scaling
        get their own plan without retyping it)."""
        import dataclasses

        from kubeoperator_tpu.utils.ids import new_id, now_ts

        source = self.repo.get_by_name(name)
        copy = dataclasses.replace(
            source, id=new_id(), created_at=now_ts(),
            name=new_name, zone_ids=list(source.zone_ids),
            vars=dict(source.vars))
        try:
            self.repo.get_by_name(new_name)
        except NotFoundError:
            return self.create(copy)
        raise ValidationError(f"plan {new_name} already exists")

    def tpu_catalog(self) -> list[dict]:
        """Selectable slice shapes for the UI wizard (topology first-class)."""
        from kubeoperator_tpu.parallel.topology import (
            GENERATIONS,
            parse_accelerator_type,
        )

        catalog = []
        for gen in GENERATIONS.values():
            sizes = sorted(set(gen.single_host_chip_sizes) | {16, 32, 64})
            for chips in sizes:
                if chips > gen.max_chips or (
                    chips not in gen.single_host_chip_sizes
                    and chips % gen.chips_per_host
                ):
                    continue
                topo = parse_accelerator_type(
                    f"{gen.name}-{gen.suffix_from_chips(chips)}"
                )
                catalog.append(topo.to_dict())
        return catalog


class HostService(_Crud):
    kind = "host"

    def __init__(self, repos: Repositories, executor: Executor) -> None:
        super().__init__(repos)
        self.executor = executor

    @property
    def repo(self):
        return self.repos.hosts

    def _pre_save(self, host: Host) -> None:
        if host.credential_id:
            self.repos.credentials.get(host.credential_id)

    def delete(self, name: str) -> None:
        host = self.repo.get_by_name(name)
        if host.cluster_id:
            raise ValidationError(
                f"host {name} is bound to a cluster; remove the node first"
            )
        self.repo.delete(host.id)

    def register(
        self, name: str, ip: str, credential_name: str, port: int = 22
    ) -> Host:
        """Manual-mode host registration (SURVEY.md §1 'Manual (bare-metal)')."""
        cred = self.repos.credentials.get_by_name(credential_name)
        host = Host(name=name, ip=ip, port=port, credential_id=cred.id)
        return self.create(host)

    def gather_facts(self, name: str) -> Host:
        """Probe the host over the executor (adhoc setup/ping)."""
        host = self.repo.get_by_name(name)
        cred = (
            self.repos.credentials.get(host.credential_id)
            if host.credential_id else None
        )
        inv = {
            "all": {
                "hosts": {
                    host.name: {
                        "ansible_host": host.ip,
                        "ansible_port": host.port,
                        **({"ansible_user": cred.username} if cred else {}),
                    }
                },
                "children": {},
            }
        }
        task_id = self.executor.run_adhoc("ping", "", inv)
        result = self.executor.wait(task_id, timeout_s=120)
        host.status = "Ready" if result.ok else "Failed"
        return self.repo.save(host)
