"""Service container/wiring (reference boot parity: config → DB+migrations →
repos → services → cron, SURVEY.md §2.1 rows 1b/1f)."""

from __future__ import annotations

from kubeoperator_tpu.executor import Executor, make_executor
from kubeoperator_tpu.provisioner import FakeProvisioner, TerraformProvisioner, terraform_available
from kubeoperator_tpu.repository import Database, Repositories
from kubeoperator_tpu.utils.config import Config, load_config
from kubeoperator_tpu.utils.logging import setup_logging


class Services:
    def __init__(
        self,
        config: Config,
        repos: Repositories,
        executor: Executor,
        provisioner: TerraformProvisioner,
    ) -> None:
        from kubeoperator_tpu.service.backup import BackupService
        from kubeoperator_tpu.service.cluster import ClusterService
        from kubeoperator_tpu.service.component import ComponentService
        from kubeoperator_tpu.service.cron import CronService
        from kubeoperator_tpu.service.event import EventService, MessageService
        from kubeoperator_tpu.service.health import HealthService
        from kubeoperator_tpu.service.infra import (
            CredentialService,
            HostService,
            PlanService,
            RegionService,
            ZoneService,
        )
        from kubeoperator_tpu.service.ldap import LdapService
        from kubeoperator_tpu.service.node import NodeService
        from kubeoperator_tpu.service.security import CisService
        from kubeoperator_tpu.service.tenancy import ProjectService, UserService
        from kubeoperator_tpu.service.upgrade import UpgradeService

        self.config = config
        self.repos = repos
        self.executor = executor
        self.provisioner = provisioner

        from kubeoperator_tpu.adm.engine import platform_vars_from_config

        # tier-1 process config → tier-3 vars contract: every phase run
        # through this stack's executor sees the configured offline-registry
        # address (scoped to the executor, not process-global)
        executor.platform_vars = platform_vars_from_config(config)

        from kubeoperator_tpu.service.notify import NotifySettingsService

        self.events = EventService(repos)
        self.messages = MessageService(repos)
        # wired here (not in run_server) so every entry point — server, CLI
        # local stack, tests — gets event→notification fan-out exactly once
        self.messages.attach_to(self.events)
        # channel wiring: stored 'notify' settings row over app.yaml
        # bootstrap values; runtime updates re-apply through this service
        self.notify_settings = NotifySettingsService(repos, self.messages,
                                                     config)
        self.notify_settings.apply()
        self.credentials = CredentialService(repos)
        self.regions = RegionService(repos)
        self.zones = ZoneService(repos)
        self.plans = PlanService(repos)
        self.hosts = HostService(repos, executor)
        self.ldap = LdapService(repos, config)
        self.users = UserService(repos, config, ldap=self.ldap)
        self.projects = ProjectService(repos)
        # ONE retry policy + jitter RNG for every phase-running service
        # (create, scale, upgrade, backup, components, CIS, guided
        # recovery), so transient-failure behavior cannot drift between
        # entry points — and ONE operation journal, so every phase loop
        # writes the same durable in-flight record the boot reconciler
        # sweeps after a controller crash
        from kubeoperator_tpu.adm import scheduler_wiring
        from kubeoperator_tpu.resilience import (
            OperationJournal,
            lease_wiring,
            retry_wiring,
        )

        retry_policy, retry_rng = retry_wiring(config)
        # ONE phase-DAG scheduler posture (scheduler.* config block) for
        # every phase-running service, same pattern as the retry pair:
        # families with declared Phase.after edges run concurrently up to
        # max_concurrent_phases, everything else stays serial
        scheduler = scheduler_wiring(config)
        # ONE lease manager per replica (lease.* config block): fenced
        # cluster ownership for the multi-controller control plane — every
        # journal op claims its cluster under this replica's stable id and
        # carries the claim's epoch as a fencing token
        # (docs/resilience.md "Controller leases")
        self.leases = lease_wiring(config, repos)
        # the journal is also the trace anchor (docs/observability.md):
        # every operation it opens gets a durable span tree under the
        # observability.* knobs
        self.journal = OperationJournal(
            repos,
            tracing=bool(config.get("observability.tracing", True)),
            max_spans_per_op=int(
                config.get("observability.max_spans_per_op", 2000)),
            retain_operations=int(
                config.get("observability.retain_operations", 200)),
            events_enabled=bool(config.get("observability.events", True)),
            retain_events=int(
                config.get("observability.retain_events", 5000)),
            max_samples_per_op=int(
                config.get("observability.max_samples_per_op", 512)),
            leases=self.leases,
        )
        # ONE slice pool (slicepool.* config block): the per-slice incident
        # ledger + degraded-mesh planner behind replace_slice and the
        # watchdog's preemption routing (docs/resilience.md "Slice
        # preemption")
        from kubeoperator_tpu.resilience import SlicePool

        self.slicepool = SlicePool(repos, config)
        self.clusters = ClusterService(
            repos, executor, provisioner, self.events, config,
            retry_policy=retry_policy, retry_rng=retry_rng,
            journal=self.journal, scheduler=scheduler,
            slicepool=self.slicepool,
        )
        self.nodes = NodeService(repos, executor, provisioner, self.events,
                                 retry_policy=retry_policy,
                                 retry_rng=retry_rng, journal=self.journal,
                                 scheduler=scheduler)
        self.upgrades = UpgradeService(repos, executor, self.events,
                                       retry_policy=retry_policy,
                                       retry_rng=retry_rng,
                                       journal=self.journal,
                                       scheduler=scheduler)
        self.backups = BackupService(repos, executor, self.events,
                                     retry_policy=retry_policy,
                                     retry_rng=retry_rng,
                                     journal=self.journal,
                                     scheduler=scheduler)
        self.health = HealthService(repos, executor, self.events,
                                    retry_policy=retry_policy,
                                    retry_rng=retry_rng,
                                    journal=self.journal,
                                    scheduler=scheduler)
        self.components = ComponentService(repos, executor, self.events,
                                           retry_policy=retry_policy,
                                           retry_rng=retry_rng,
                                           journal=self.journal,
                                           scheduler=scheduler)
        self.cis = CisService(repos, executor, self.events,
                              retry_policy=retry_policy,
                              retry_rng=retry_rng, journal=self.journal,
                              scheduler=scheduler)
        from kubeoperator_tpu.service.watchdog import WatchdogService

        self.watchdog = WatchdogService(repos, self.health, self.events,
                                        config, clusters=self.clusters,
                                        slicepool=self.slicepool)
        # fleet orchestration rides on everything above: journaled child
        # ops through UpgradeService, gates through health+watchdog, all
        # stitched under one fleet op/trace (docs/resilience.md)
        from kubeoperator_tpu.service.fleet import FleetService

        self.fleet = FleetService(self)
        # tenant workloads ride the same journal/trace/lease spine: a
        # training run is a platform operation like any other
        from kubeoperator_tpu.service.workload import WorkloadService

        self.workloads = WorkloadService(self)
        # the preemption-NOTICE handler drains running workloads (they
        # checkpoint at the next step boundary) before the watchdog
        # drives the slice replacement — wired after construction because
        # the watchdog is built before the workload service exists
        self.watchdog.workloads = self.workloads
        # torn-checkpoint sweep BEFORE anything can resume: directories a
        # dead controller left without a manifest are debris, never a
        # restore source (docs/workloads.md "Checkpoints")
        self.checkpoint_sweep_report = self.workloads.sweep_torn()
        # the workload QUEUE rides the workload service: submissions are
        # journaled platform ops, gang scheduling packs them onto
        # slice-pool capacity, priority preemption drains victims through
        # the checkpoint machinery above (docs/workloads.md "Queue and
        # preemption")
        from kubeoperator_tpu.service.queue import WorkloadQueueService

        self.workload_queue = WorkloadQueueService(self)
        # the convergence controller closes the drift loop: detect_drift's
        # remediation set, re-run every `converge.interval_s`, becomes
        # journaled remediation-tenant queue entries executed through the
        # fleet/retry/recovery verbs above (docs/resilience.md "Fleet
        # convergence"); the cron loop kicks it, always off-thread
        from kubeoperator_tpu.service.converge import ConvergeService

        self.converge = ConvergeService(self)
        self.cron = CronService(self)
        from kubeoperator_tpu.terminal import TerminalManager

        self.terminals = TerminalManager(repos, config)

        # boot reconciliation LAST, once every service exists: sweep
        # operations orphaned by the previous controller's death and (per
        # resilience.reconcile.auto_resume) re-enter their resume paths —
        # no operation thread can be running yet, so every open journal op
        # is by construction an orphan
        from kubeoperator_tpu.service.reconcile import ReconcileService

        self.reconciler = ReconcileService(self)
        self.boot_report = self.reconciler.boot_sweep()

    def close(self) -> None:
        self.cron.stop()
        self.terminals.shutdown()
        self.converge.wait_all()
        self.fleet.wait_all()
        self.clusters.wait_all()
        self.workload_queue.wait_all()
        self.workloads.wait_all()
        self.repos.db.close()


def build_services(
    config: Config | None = None, simulate: bool | None = None
) -> Services:
    """Wire the full stack. `simulate=None` auto-detects: real backends when
    the binaries exist, simulation otherwise (air-gapped demo parity)."""
    config = config or load_config()
    setup_logging(
        config.get("logging.level", "INFO"), config.get("logging.dir"),
        json_logs=bool(config.get("observability.json_logs", False)),
    )
    telemetry = None
    if config.get("observability.db_telemetry", True):
        # the control-plane flight recorder (docs/observability.md
        # "Control-plane DB telemetry"): statement-level lock-wait/exec/
        # commit attribution this replica's /metrics and `koctl db stats`
        # read back. Constructed BEFORE the Database so the migration
        # runner's statements are recorded too.
        from kubeoperator_tpu.observability.dbtelemetry import DbTelemetry

        telemetry = DbTelemetry(
            path=str(config.get("db.path", "ko_tpu.db")),
            max_statements=int(config.get(
                "observability.db_telemetry_max_statements", 256)))
    db = Database(config.get("db.path", "ko_tpu.db"),
                  synchronous=str(config.get("db.synchronous", "NORMAL")),
                  busy_timeout_ms=int(config.get("db.busy_timeout_ms", 5000)),
                  telemetry=telemetry)
    repos = Repositories(db)
    from kubeoperator_tpu.utils.i18n import set_default_locale

    set_default_locale(config.get("i18n.default_locale", "en-US"))
    backend = config.get("executor.backend", "auto")
    executor = make_executor(
        backend,
        config.get("executor.project_dir"),
        runner_address=config.get("executor.runner_address"),
        fork_limit=int(config.get("executor.fork_limit", 32)),
    )
    # the default watch/wait ceiling for un-deadlined tasks — applied
    # below, after any chaos wrapping, because the outermost executor is
    # the one whose task registry watch/wait consult
    task_timeout_s = float(config.get("executor.task_timeout_s", 7200))
    if config.get("chaos.enabled", False):
        # seeded fault injection (resilience/chaos.py): the stack behaves
        # identically to production EXCEPT tasks randomly fail in transient
        # ways — the harness `koctl chaos-soak` builds on. The wrapper owns
        # the task registry; the configured backend runs underneath.
        if backend == "grpc":
            # RunnerClient executes remotely and has no in-process _execute
            # for the wrapper to delegate to — fail at build time, not with
            # an opaque NotImplementedError on the first phase
            from kubeoperator_tpu.utils.errors import ValidationError

            raise ValidationError(
                "chaos.enabled requires an in-process executor backend "
                "(simulation/ansible/fake), not executor.backend=grpc — "
                "enable chaos on the ko-runner process instead"
            )
        import random

        from kubeoperator_tpu.resilience import ChaosConfig, ChaosExecutor

        executor = ChaosExecutor(
            executor,
            rng=random.Random(int(config.get("chaos.seed", 1))),
            config=ChaosConfig.from_config(config),
        )
    executor.task_timeout_s = task_timeout_s
    if simulate is None:
        simulate = not terraform_available(
            config.get("provisioner.terraform_bin", "terraform")
        )
    prov_cls = FakeProvisioner if simulate else TerraformProvisioner
    raw_timeout = config.get("provisioner.timeout_s", 3600)
    try:
        timeout_s = float(raw_timeout)
    except (TypeError, ValueError):
        from kubeoperator_tpu.utils.errors import ValidationError

        raise ValidationError(
            f"provisioner.timeout_s must be a number of seconds, "
            f"got {raw_timeout!r}"
        )
    from kubeoperator_tpu.resilience import RetryPolicy

    provisioner = prov_cls(
        work_dir=config.get("provisioner.work_dir", "terraform_runs"),
        terraform_bin=config.get("provisioner.terraform_bin", "terraform"),
        timeout_s=timeout_s,
        retry_policy=RetryPolicy(
            max_attempts=int(config.get("provisioner.retry_max_attempts", 2)),
            backoff_base_s=float(config.get("provisioner.retry_backoff_s", 5)),
            jitter_ratio=0.0,
        ),
    )
    return Services(config, repos, executor, provisioner)
