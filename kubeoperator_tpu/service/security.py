"""CisService — CIS benchmark scans via kube-bench (SURVEY.md §1 'Day-2
operations: CIS security scans (kube-bench)').

Flow mirrors the smoke test's marker contract: the cis-scan role condenses
kube-bench output into one `KO_CIS_RESULT {json}` line; the adm post-hook
parses it into the scan row. Scans persist per cluster so the UI/CLI can show
a findings history; a Failed grade raises a Warning event (message center
fan-out picks it up).
"""

from __future__ import annotations

from kubeoperator_tpu.adm import AdmContext, ClusterAdm
from kubeoperator_tpu.adm.engine import Phase
from kubeoperator_tpu.adm.phases import parse_marker_json
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import CisCheck, CisScan
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import NotFoundError, PhaseError, ValidationError

CIS_MARKER = "KO_CIS_RESULT"


def parse_cis_result(lines: list[str]) -> dict | None:
    return parse_marker_json(CIS_MARKER, lines)


class CisService:
    def __init__(self, repos: Repositories, executor: Executor, events,
                 retry_policy=None, retry_rng=None, journal=None,
                 scheduler=None):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng,
                              scheduler=scheduler)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    def run_scan(self, cluster_name: str) -> CisScan:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("CIS scan")
        if not self.repos.nodes.find(cluster_id=cluster.id):
            raise ValidationError(
                f"cluster {cluster_name} has no nodes to scan"
            )
        scan = CisScan(cluster_id=cluster.id)
        scan.validate()
        self.repos.cis_scans.save(scan)

        def post(ctx: AdmContext, result, lines: list[str]) -> None:
            data = parse_cis_result(lines)
            if data is None:
                raise PhaseError("cis-scan", "no KO_CIS_RESULT in scan output")
            scan.policy = str(data.get("policy") or scan.policy)
            scan.total_pass = int(data.get("pass") or 0)
            scan.total_fail = int(data.get("fail") or 0)
            scan.total_warn = int(data.get("warn") or 0)
            scan.total_info = int(data.get("info") or 0)
            scan.checks = [
                CisCheck(
                    id=str(c.get("id", "")), text=str(c.get("text", "")),
                    status=str(c.get("status", "")), node=str(c.get("node", "")),
                    remediation=str(c.get("remediation", "")),
                )
                for c in data.get("checks", [])
                if isinstance(c, dict)
            ]

        plan = self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None
        ctx = AdmContext.for_cluster(self.repos, cluster, plan)
        op = self.journal.open(cluster, "cis-scan")
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, [Phase("cis-scan", "50-cis-scan.yml", post=post)])
        except PhaseError as e:
            scan.status = "Error"
            scan.message = e.message
            self.repos.cis_scans.save(scan)
            self.journal.close(op, ok=False, message=e.message)
            raise
        self.journal.close(op, ok=True)
        scan.status = scan.grade()
        self.repos.cis_scans.save(scan)
        if scan.status == "Failed":
            self.events.emit(
                cluster.id, "Warning", "CisScanFailed",
                f"CIS scan found {scan.total_fail} failing checks on "
                f"{cluster_name}",
            )
        else:
            self.events.emit(
                cluster.id, "Normal", "CisScanCompleted",
                f"CIS scan {scan.status.lower()} on {cluster_name} "
                f"(pass={scan.total_pass} warn={scan.total_warn})",
            )
        return scan

    def list(self, cluster_name: str) -> list[CisScan]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.cis_scans.find(cluster_id=cluster.id)

    def get(self, cluster_name: str, scan_id: str) -> CisScan:
        """Scan lookup scoped to the cluster the caller was authorized for —
        a scan id from another cluster must 404, not leak findings."""
        return self._owned_scan(cluster_name, scan_id)

    def delete(self, cluster_name: str, scan_id: str) -> None:
        self._owned_scan(cluster_name, scan_id)
        self.repos.cis_scans.delete(scan_id)

    def _owned_scan(self, cluster_name: str, scan_id: str) -> CisScan:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        scan = self.repos.cis_scans.get(scan_id)
        if scan.cluster_id != cluster.id:
            raise NotFoundError(kind="cis_scan", name=scan_id)
        return scan
