"""Message-center delivery channels (SURVEY.md §1 'message center
(email/webhook notifications)', §5.5).

`MessageService.senders` is the fan-out registry; this module supplies the
two reference channels — SMTP email and JSON webhook — and wires them from
config at boot (`configure_senders`). Sender failures are logged and
swallowed by MessageService so a dead mail relay can never block an event
flow.
"""

from __future__ import annotations

import json
import smtplib
import urllib.request
from email.message import EmailMessage

from kubeoperator_tpu.models import Message
from kubeoperator_tpu.utils.config import Config
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.notify")


class SmtpSender:
    """Email channel. Recipient resolution: the message's user row email."""

    def __init__(self, repos, host: str, port: int = 25, username: str = "",
                 password: str = "", sender: str = "ko-tpu@localhost",
                 use_tls: bool = False, timeout_s: float = 10.0):
        self.repos = repos
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.sender = sender
        self.use_tls = use_tls
        self.timeout_s = timeout_s

    def __call__(self, message: Message) -> None:
        user = self.repos.users.get(message.user_id)
        if not user.email:
            return  # nothing to deliver to; in-app copy already stored
        mail = EmailMessage()
        mail["From"] = self.sender
        mail["To"] = user.email
        mail["Subject"] = f"[ko-tpu][{message.level}] {message.title}"
        mail.set_content(message.content)
        with smtplib.SMTP(self.host, self.port,
                          timeout=self.timeout_s) as smtp:
            if self.use_tls:
                smtp.starttls()
            if self.username:
                smtp.login(self.username, self.password)
            smtp.send_message(mail)
        log.info("mailed %s to %s", message.title, user.email)


class WebhookSender:
    """POSTs the message as JSON to a fixed endpoint (chat-ops integrations)."""

    def __init__(self, url: str, timeout_s: float = 10.0,
                 headers: dict | None = None):
        self.url = url
        self.timeout_s = timeout_s
        self.headers = {"Content-Type": "application/json", **(headers or {})}

    def __call__(self, message: Message) -> None:
        payload = json.dumps({
            "title": message.title,
            "content": message.content,
            "level": message.level,
            "user_id": message.user_id,
            "ts": message.created_at,
        }).encode()
        req = urllib.request.Request(self.url, data=payload,
                                     headers=self.headers, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"webhook returned {resp.status}")
        log.info("webhook delivered %s", message.title)


def configure_senders(messages, repos, config: Config) -> None:
    """Attach the channels the operator enabled in config."""
    if config.get("notify.smtp.enabled", False):
        messages.senders["smtp"] = SmtpSender(
            repos,
            host=config.get("notify.smtp.host", "localhost"),
            port=int(config.get("notify.smtp.port", 25)),
            username=config.get("notify.smtp.username", ""),
            password=config.get("notify.smtp.password", ""),
            sender=config.get("notify.smtp.from", "ko-tpu@localhost"),
            use_tls=bool(config.get("notify.smtp.tls", False)),
        )
    if config.get("notify.webhook.url", ""):
        messages.senders["webhook"] = WebhookSender(
            config.get("notify.webhook.url"),
            headers=config.get("notify.webhook.headers", {}) or {},
        )
