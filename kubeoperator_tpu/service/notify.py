"""Message-center delivery channels (SURVEY.md §1 'message center
(email/webhook notifications)', §5.5).

`MessageService.senders` is the fan-out registry; this module supplies the
two reference channels — SMTP email and JSON webhook — plus
NotifySettingsService, the ONE wiring path: defaults <- app.yaml <- the
stored 'notify' overrides row, applied at boot and re-applied on every
runtime update. Sender failures are logged and swallowed by
MessageService so a dead mail relay can never block an event flow.
"""

from __future__ import annotations

import json
import smtplib
import threading
import urllib.request
from email.message import EmailMessage

from kubeoperator_tpu.models import Message
from kubeoperator_tpu.utils.config import Config
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.notify")


class SmtpSender:
    """Email channel. Recipient resolution: the message's user row email."""

    def __init__(self, repos, host: str, port: int = 25, username: str = "",
                 password: str = "", sender: str = "ko-tpu@localhost",
                 use_tls: bool = False, timeout_s: float = 10.0):
        self.repos = repos
        self.host = host
        self.port = port
        self.username = username
        self.password = password
        self.sender = sender
        self.use_tls = use_tls
        self.timeout_s = timeout_s

    def __call__(self, message: Message) -> None:
        user = self.repos.users.get(message.user_id)
        if not user.email:
            return  # nothing to deliver to; in-app copy already stored
        mail = EmailMessage()
        mail["From"] = self.sender
        mail["To"] = user.email
        mail["Subject"] = f"[ko-tpu][{message.level}] {message.title}"
        mail.set_content(message.content)
        with smtplib.SMTP(self.host, self.port,
                          timeout=self.timeout_s) as smtp:
            if self.use_tls:
                smtp.starttls()
            if self.username:
                smtp.login(self.username, self.password)
            smtp.send_message(mail)
        log.info("mailed %s to %s", message.title, user.email)


class WebhookSender:
    """POSTs the message as JSON to a fixed endpoint (chat-ops integrations)."""

    def __init__(self, url: str, timeout_s: float = 10.0,
                 headers: dict | None = None):
        self.url = url
        self.timeout_s = timeout_s
        self.headers = {"Content-Type": "application/json", **(headers or {})}

    def __call__(self, message: Message) -> None:
        payload = json.dumps({
            "title": message.title,
            "content": message.content,
            "level": message.level,
            "user_id": message.user_id,
            "ts": message.created_at,
        }).encode()
        req = urllib.request.Request(self.url, data=payload,
                                     headers=self.headers, method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout_s) as resp:
            if resp.status >= 300:
                raise RuntimeError(f"webhook returned {resp.status}")
        log.info("webhook delivered %s", message.title)


# ---------------------------------------------------------------------------
# runtime-editable channel settings (SURVEY.md §5.6: the reference keeps
# message-center settings in a DB table behind an admin UI, not app.yaml)
# ---------------------------------------------------------------------------

NOTIFY_DEFAULTS = {
    "smtp": {"enabled": False, "host": "localhost", "port": 25,
             "username": "", "password": "", "sender": "ko-tpu@localhost",
             "use_tls": False},
    # headers: extra HTTP headers (auth tokens for chat-ops endpoints) —
    # settable via the API; header VALUES are masked on read
    "webhook": {"enabled": False, "url": "", "headers": {}},
}
# (channel, key) pairs whose values the read API must mask — same
# discipline as the provider-vars contract's secret keys. webhook.headers
# is masked per-header-value (Authorization tokens live there).
NOTIFY_SECRET_KEYS = (("smtp", "password"),)

_MASK = "********"


class NotifySettingsService:
    """Get/update/test the message-center channels at runtime.

    Storage model: the 'notify' settings row holds ONLY the operator's
    explicit overrides; reads merge defaults <- app.yaml <- overrides.
    Persisting the merged document instead would freeze every app.yaml
    value (including its SMTP password) into the DB at first save, and a
    later config rotation would silently lose to the stale copy. Every
    update re-wires MessageService.senders immediately, and `test` pushes
    a real probe through the chosen sender so a dead relay is discovered
    at configure time, not at the next 2am Warning."""

    def __init__(self, repos, messages, config: Config):
        self.repos = repos
        self.messages = messages
        self.config = config
        # update() is read-modify-write over one row; concurrent admin
        # PUTs (thread-pool handlers) must not lose each other's overrides
        self._write_lock = threading.Lock()

    # ---- settings document ----
    def _stored_overrides(self) -> dict:
        from kubeoperator_tpu.utils.errors import NotFoundError

        try:
            return self.repos.settings.get_by_name("notify").vars
        except NotFoundError:
            # ONLY not-found means "no overrides yet" — a sick DB must
            # surface, not silently wire channels from defaults alone
            return {}

    def effective(self) -> dict:
        out = {ch: dict(defaults) for ch, defaults in NOTIFY_DEFAULTS.items()}
        # bootstrap tier: app.yaml (the historical config keys), so an
        # existing deployment keeps working untouched
        out["smtp"].update({
            "enabled": bool(self.config.get("notify.smtp.enabled", False)),
            "host": self.config.get("notify.smtp.host", "localhost"),
            "port": int(self.config.get("notify.smtp.port", 25)),
            "username": self.config.get("notify.smtp.username", ""),
            "password": self.config.get("notify.smtp.password", ""),
            "sender": self.config.get("notify.smtp.from", "ko-tpu@localhost"),
            "use_tls": bool(self.config.get("notify.smtp.tls", False)),
        })
        url = self.config.get("notify.webhook.url", "")
        if url:
            out["webhook"].update({
                "enabled": True, "url": url,
                "headers": self.config.get("notify.webhook.headers", {})
                or {},
            })
        # runtime tier: the operator's explicit overrides win. headers
        # merge PER NAME over the config tier (a flat replace would let a
        # stored {X-Extra: v} silently drop app.yaml's Authorization);
        # an empty-string value deletes that header at apply time.
        for channel, values in self._stored_overrides().items():
            if channel in out and isinstance(values, dict):
                for key, value in values.items():
                    if key == "headers" and isinstance(value, dict):
                        out[channel]["headers"] = {
                            **out[channel].get("headers", {}), **value}
                    else:
                        out[channel][key] = value
        return out

    def get_public(self) -> dict:
        doc = self.effective()
        for channel, key in NOTIFY_SECRET_KEYS:
            if doc.get(channel, {}).get(key):
                doc[channel][key] = _MASK
        doc["webhook"]["headers"] = {
            name: _MASK for name in doc["webhook"].get("headers", {})
        }
        return doc

    def update(self, body: dict) -> dict:
        with self._write_lock:
            return self._update_locked(body)

    def _update_locked(self, body: dict) -> dict:
        from kubeoperator_tpu.models import Setting
        from kubeoperator_tpu.utils.errors import NotFoundError, ValidationError

        stored = {ch: dict(v) for ch, v in self._stored_overrides().items()}
        for channel, values in body.items():
            if channel not in NOTIFY_DEFAULTS:
                raise ValidationError(f"unknown notify channel {channel!r}")
            if not isinstance(values, dict):
                raise ValidationError(f"{channel} settings must be an object")
            overrides = stored.setdefault(channel, {})
            for key, value in values.items():
                if key not in NOTIFY_DEFAULTS[channel]:
                    raise ValidationError(
                        f"unknown {channel} setting {key!r}")
                default = NOTIFY_DEFAULTS[channel][key]
                if isinstance(default, bool) and not isinstance(value, bool):
                    raise ValidationError(
                        f"{channel}.{key} must be a boolean, got {value!r}")
                if isinstance(default, dict) and not isinstance(value, dict):
                    raise ValidationError(
                        f"{channel}.{key} must be an object, got {value!r}")
                # non-bool/dict settings are typed by their default too: an
                # int where smtplib expects a username string would only
                # explode (swallowed) at delivery time
                if isinstance(default, int) and not isinstance(default, bool) \
                        and (not isinstance(value, int)
                             or isinstance(value, bool)):
                    # bool subclasses int: port=true would pass a bare
                    # isinstance and connect to port 1
                    raise ValidationError(
                        f"{channel}.{key} must be an integer, got {value!r}")
                if isinstance(default, str) and not isinstance(value, str):
                    raise ValidationError(
                        f"{channel}.{key} must be a string, got {value!r}")
                # a round-tripped mask means "unchanged": keep the stored
                # override if one exists, else DROP the key so app.yaml
                # keeps supplying it (never copy config secrets into the DB)
                if (channel, key) in NOTIFY_SECRET_KEYS and value == _MASK:
                    continue
                if key == "headers" and isinstance(value, dict):
                    stored_headers = overrides.get("headers", {})
                    cleaned = {}
                    for name, v in value.items():
                        if v == _MASK:
                            # same mask rule per header: keep the stored
                            # override; a masked config-sourced header
                            # stays config-sourced (never copied, never
                            # blanked)
                            if name in stored_headers:
                                cleaned[name] = stored_headers[name]
                        else:
                            cleaned[name] = str(v)
                    # merge per NAME into the stored overrides — the write
                    # path must honor the same semantics the read path
                    # promises, or a partial update silently drops every
                    # header override it didn't mention
                    value = {**stored_headers, **cleaned}
                overrides[key] = value

        # validate the EFFECTIVE result of applying these overrides
        merged = self.effective()
        for ch in merged:
            merged[ch].update(stored.get(ch, {}))
        port = merged["smtp"].get("port")
        if not isinstance(port, int) or not 1 <= port <= 65535:
            raise ValidationError(f"smtp.port must be 1-65535, got {port!r}")
        if merged["webhook"]["enabled"] and not str(
                merged["webhook"]["url"]).startswith(
                ("http://", "https://")):
            raise ValidationError("webhook.url must be http(s)://...")

        try:
            row = self.repos.settings.get_by_name("notify")
        except NotFoundError:
            row = Setting(name="notify")
        row.vars = stored
        self.repos.settings.save(row)
        self.apply()
        return self.get_public()

    # ---- live wiring ----
    def apply(self) -> None:
        """The ONE channel-wiring path (boot + every runtime update)."""
        doc = self.effective()
        self.messages.senders.pop("smtp", None)
        self.messages.senders.pop("webhook", None)
        if doc["smtp"]["enabled"]:
            self.messages.senders["smtp"] = SmtpSender(
                self.repos,
                host=doc["smtp"]["host"], port=int(doc["smtp"]["port"]),
                username=doc["smtp"]["username"],
                password=doc["smtp"]["password"],
                sender=doc["smtp"]["sender"],
                use_tls=bool(doc["smtp"]["use_tls"]),
            )
        if doc["webhook"]["enabled"] and doc["webhook"]["url"]:
            # empty-valued headers are deletions (the override tier's way
            # to remove a config-supplied header)
            headers = {name: v for name, v in
                       (doc["webhook"].get("headers", {}) or {}).items()
                       if v}
            self.messages.senders["webhook"] = WebhookSender(
                doc["webhook"]["url"], headers=headers,
            )

    def test(self, channel: str, user_id: str) -> dict:
        """Push a real probe through one sender NOW. Errors come back as
        data (not exceptions): a failed relay is the expected case this
        exists to surface."""
        from kubeoperator_tpu.utils.errors import ValidationError

        if channel not in NOTIFY_DEFAULTS:
            raise ValidationError(f"unknown notify channel {channel!r}")
        sender = self.messages.senders.get(channel)
        if sender is None:
            return {"ok": False,
                    "error": f"{channel} channel is not enabled"}
        if channel == "smtp":
            # SmtpSender silently no-ops for address-less users — correct
            # for the event fan-out, but a TEST that no-ops would report a
            # dead relay as healthy
            user = self.repos.users.get(user_id)
            if not getattr(user, "email", ""):
                return {"ok": False,
                        "error": "your account has no email address; "
                                 "set one to receive mail"}
        probe = Message(user_id=user_id, title="Test notification",
                        content="ko-tpu message-center connectivity test",
                        level="info")
        try:
            sender(probe)
        except Exception as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
        return {"ok": True}
