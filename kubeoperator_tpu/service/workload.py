"""WorkloadService — sharded training as a first-class platform workload.

`koctl workload train --plan <plan> --mesh data=4,fsdp=2` lands here: the
(data, fsdp, tp) mesh is parsed through the declarative MeshSpec, the
partition-rule engine produces the layout (and its coverage report), and
the run executes as a JOURNALED operation — so a tenant training run
inherits everything cluster operations already have: the durable journal
row (PR 3), the persisted span tree with step-window spans under the op
root (`koctl workload trace` waterfalls, PR 5), and lease fencing in
multi-controller stacks (PR 8) for free, because every one of those
rides the journal the run writes through.

Scope: like a fleet rollout, a workload op belongs to the PLATFORM, not
to one cluster (`cluster_id == ""`, marker ``(workload)``); the lease
resource is the op's own id.

Durable training (ISSUE 11): every completed (or drained) run saves a
sharded, content-hashed checkpoint of the FULL TrainState — params plus
adamw optimizer state — through workloads/checkpoint.py, indexed by
`CheckpointRepo`. `train --resume [--checkpoint id]` restores the real
step/optimizer state and continues the exact trajectory (the resumed op
stitches into the original run's trace, so the interrupted life renders
as one waterfall); a preemption NOTICE (service/watchdog.py) calls
`request_drain`, the step loop checkpoints at the next boundary, and the
op closes "drained" with a restorable checkpoint — BEFORE the chips
vanish. Orphaned workload ops sweep to Interrupted at boot naming the
latest complete checkpoint as the resume point (service/reconcile.py).

`--plan` pins the run to a deploy plan's TPU topology: the visible
device count must match the plan, and the plan's generation supplies the
MFU datasheet peak and ICI envelope context. Without a plan the run uses
whatever devices are visible (the tier-1 path: 8 host-platform CPU
devices).
"""

from __future__ import annotations

import os
import shutil
import threading
import time

from kubeoperator_tpu.models import Checkpoint, Operation
from kubeoperator_tpu.utils.errors import (
    KoError,
    NotFoundError,
    ValidationError,
)
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.workload")

WORKLOAD_TRAIN_KIND = "workload-train"
WORKLOAD_SWEEP_KIND = "workload-sweep"
WORKLOAD_SERVE_KIND = "workload-serve"


class _StepSampler:
    """Per-step training telemetry producer riding the `on_step` seam
    (workloads/harness.py run_training): measures the wall-clock between
    step boundaries, fetches the device loss, and lands ONE MetricSample
    per step through the op's tracer — samples flush with the span
    buffer, one commit per boundary, so `koctl workload watch` reads a
    live tail while the run is still stepping. The loss fetch blocks the
    step loop by design (the harness documents that hook cost rides the
    timed window); the tier-1 overhead budget pins the whole layer under
    5%. A NullTracer (tracing off) never constructs one of these."""

    def __init__(self, journal, op, *, flops_per_step: float,
                 peak_tflops_per_chip: float | None, devices: int,
                 tenant: str = "", base_step: int = 0) -> None:
        self.journal = journal
        self.op = op
        self.flops = float(flops_per_step)
        self.peak = peak_tflops_per_chip or 0.0
        self.devices = max(int(devices), 1)
        self.tenant = tenant
        self.base_step = int(base_step)
        self._last: float | None = None

    def __call__(self, completed: int, loss) -> None:
        import jax

        from kubeoperator_tpu.models import MetricSample

        now = time.perf_counter()
        # Step wall-clock splits in two at this seam: `input_s` is the
        # host-side share (data/dispatch between the previous loss fetch
        # returning and this boundary firing — async dispatch means the
        # device may overlap it, but the host was *here*), `compute_s`
        # is the blocking device_get, which rides the device until the
        # step's result materializes. The first boundary follows the
        # compile, not a step, so both halves report 0 (unknown).
        input_s = (now - self._last) if self._last is not None else 0.0
        loss_value = float(jax.device_get(loss))
        fetched = time.perf_counter()
        compute_s = (fetched - now) if self._last is not None else 0.0
        self._last = fetched
        step_s = input_s + compute_s
        steps_per_s = round(1.0 / step_s, 3) if step_s > 0 else 0.0
        tflops = (round(self.flops * steps_per_s / 1e12, 4)
                  if steps_per_s else 0.0)
        mfu = (round(100.0 * tflops / (self.peak * self.devices), 3)
               if self.peak and tflops else 0.0)
        self.journal.record_samples(self.op, [MetricSample(
            op_id=self.op.id, step=self.base_step + int(completed),
            kind="step", tenant=self.tenant,
            loss=loss_value,
            step_s=round(step_s, 6), steps_per_s=steps_per_s,
            tflops=tflops, mfu_pct=mfu,
            attrs={"input_s": round(input_s, 6),
                   "compute_s": round(compute_s, 6)},
        )])



def train_kwargs(body: dict) -> dict:
    """The body→`WorkloadService.train` translation BOTH transports share
    (REST handler and `LocalClient._dispatch`) — the behavioral half of
    the KO-X010 parity contract, same pattern as fleet's
    `upgrade_kwargs`."""
    from kubeoperator_tpu.fleet.planner import optional_int

    resume = body.get("resume", False)
    if not isinstance(resume, bool):
        raise ValidationError("resume must be a boolean")
    return {
        "plan": str(body.get("plan", "") or ""),
        "mesh": str(body.get("mesh", "") or ""),
        "steps": optional_int("steps", body.get("steps")),
        "mode": str(body.get("mode", "") or ""),
        "resume": resume,
        "checkpoint": str(body.get("checkpoint", "") or ""),
        "tenant": str(body.get("tenant", "") or ""),
    }


class WorkloadService:
    def __init__(self, services) -> None:
        self.s = services
        self.repos = services.repos
        self.journal = services.journal
        cfg = services.config
        self.default_steps = int(cfg.get("workloads.steps", 4))
        self.default_mesh = str(cfg.get("workloads.mesh", "") or "")
        self.default_mode = str(cfg.get("workloads.mode", "auto"))
        self.peak_override = float(
            cfg.get("workloads.peak_tflops_per_chip", 0.0))
        # durable-training checkpoints (checkpoint.* DEFAULTS block)
        self.ckpt_enabled = bool(cfg.get("checkpoint.enabled", True))
        self.ckpt_keep = max(int(cfg.get("checkpoint.keep", 5)), 1)
        # periodic mid-run saves every N step boundaries (0 = end-of-run
        # and drain saves only) — rides the on_step boundary seam
        self.ckpt_every = max(int(cfg.get("checkpoint.every_steps", 0)), 0)
        self.ckpt_dir = self._resolve_ckpt_dir(
            str(cfg.get("checkpoint.dir", "") or ""),
            str(cfg.get("db.path", "") or ""))
        # serving defaults (serve.* DEFAULTS block, docs/workloads.md
        # "Serving"): requests answered per session, and the per-request
        # latency SLO the session's verdict is judged against (0 = no SLO)
        self.serve_requests = max(int(cfg.get("serve.requests", 8)), 1)
        self.serve_slo_ms = float(cfg.get("serve.slo_ms", 0.0))
        # cooperative drain, PER RUN (ISSUE 18): concurrent dispatch means
        # N live runs, so the drain flag is a registry keyed by the run's
        # dispatch key (queue entry op id, or the run op's own id) — two
        # victims draining concurrently each consume their OWN flag, and a
        # serving run's degrade directives queue on its own control lane.
        # step_hook/request_hook are the per-boundary seams drills compose
        # onto (called before the drain check).
        self._runs_lock = threading.Lock()
        self._runs: dict[str, dict] = {}
        self.step_hook = None
        self.request_hook = None
        # background resume threads (the reconciler's auto-resume path):
        # joined by wait_all() at container close, like cluster op threads
        self._threads: list[threading.Thread] = []

    @staticmethod
    def _resolve_ckpt_dir(configured: str, db_path: str) -> str:
        """`checkpoint.dir`, defaulting to a `checkpoints/` dir NEXT TO
        the SQLite file — the index rows and the shard files share fate
        (one tmp stack, one data dir), and test stacks inherit isolation
        from their tmp db paths for free. :memory: stacks fall back to
        ./checkpoints."""
        if configured:
            return configured
        if db_path and db_path != ":memory:":
            return os.path.join(os.path.dirname(db_path) or ".",
                                "checkpoints")
        return "checkpoints"

    # ---- cooperative drain (preemption notice integration) ----
    def _register_run(self, key: str, kind: str) -> dict:
        """Open one run's drain/control lane under `key` (its dispatch
        key: the queue entry's op id for dispatched runs, the run op's
        own id otherwise). The record is the run's PRIVATE fault-isolation
        surface — a sibling's drain or crash never touches it."""
        rec = {"drain": threading.Event(), "reason": "", "kind": kind,
               "control": []}
        with self._runs_lock:
            self._runs[key] = rec
        return rec

    def _unregister_run(self, key: str) -> None:
        with self._runs_lock:
            self._runs.pop(key, None)

    def request_drain(self, reason: str = "drain requested",
                      target: str = "") -> None:
        """Ask a running loop to checkpoint and stop at its next
        boundary. `target` names ONE run's dispatch key (the queue's
        per-victim preemption path); empty target drains EVERY live run
        — the preemption-notice/watchdog posture, where the chips under
        all of them are about to vanish. Safe with nothing running."""
        with self._runs_lock:
            if target:
                recs = ([self._runs[target]]
                        if target in self._runs else [])
            else:
                recs = list(self._runs.values())
        for rec in recs:
            rec["reason"] = reason
            rec["drain"].set()
        log.info("workload drain requested (%s): %s",
                 target[:8] if target else "all", reason)

    def request_degrade(self, target: str, mesh) -> bool:
        """Queue a ``("reshard", mesh)`` directive for ONE live serving
        run (`target` = its dispatch key): at its next request boundary
        the server re-compiles onto `mesh` (a built Mesh or a MeshSpec
        over the survivors) and keeps answering at reduced throughput —
        the degrade-not-die half of the slice-preemption contract.
        Returns False when no such run is live (caller falls back to a
        drain)."""
        with self._runs_lock:
            rec = self._runs.get(target)
        if rec is None or rec["kind"] != "serve":
            return False
        rec["control"].append(("reshard", mesh))
        log.info("workload degrade requested (%s): reshard onto %s",
                 target[:8], mesh)
        return True

    def has_running(self) -> bool:
        """A workload-train/-serve journal op is currently Running — the
        journal-row truth the notice handler consults (not thread state:
        journal rows survive whatever the threads do)."""
        from kubeoperator_tpu.models import OperationStatus

        return bool(
            self.repos.operations.find(
                kind=WORKLOAD_TRAIN_KIND,
                status=OperationStatus.RUNNING.value)
            or self.repos.operations.find(
                kind=WORKLOAD_SERVE_KIND,
                status=OperationStatus.RUNNING.value))

    def _on_step(self, rec: dict, completed: int, loss) -> bool:
        hook = self.step_hook
        if hook is not None:
            hook(completed, loss)
        return rec["drain"].is_set()

    def resume_from(self, checkpoint: str = "", tenant: str = "",
                    wait: bool = True):
        """Resume the latest (or named) complete checkpoint. With
        `wait=False` the run happens on a background thread — the
        reconciler's auto-resume posture: a boot or lease sweep must not
        block its own thread (which also carries the lease heartbeat
        tick) behind a compile+train. `tenant` keeps the resumed run in
        the interrupted op's namespace (resolution AND the new run's own
        saves). Failures on the thread surface as a Failed journal op
        plus a log line, same as any train."""
        if wait:
            return self.train(resume=True, checkpoint=checkpoint,
                              tenant=tenant)

        def run() -> None:
            try:
                self.train(resume=True, checkpoint=checkpoint,
                           tenant=tenant)
            except Exception as e:
                log.warning("background workload resume (checkpoint %r) "
                            "failed: %s", checkpoint, e)

        from kubeoperator_tpu.utils.threads import spawn

        t = spawn(f"workload-resume-{checkpoint or 'latest'}", run,
                  start=False)
        self._threads.append(t)
        t.start()
        return None

    def wait_all(self, timeout_s: float = 120.0) -> None:
        """Join background resume threads (container close)."""
        for t in self._threads:
            t.join(timeout_s)
        self._threads = [t for t in self._threads if t.is_alive()]

    # ---- the workload verb ----
    def train(self, plan: str = "", mesh: str = "", steps: int | None = None,
              mode: str = "", resume: bool = False,
              checkpoint: str = "", tenant: str = "",
              trace: dict | None = None, parent_op_id: str = "") -> dict:
        """One sharded training run as a journaled operation; returns the
        op description including the run result, rule coverage, and the
        checkpoint it saved. With `resume`, the run restores the full
        TrainState (params + optimizer moments + step counter) from the
        named (or latest) complete checkpoint and continues the exact
        trajectory — default step count is what the original run had
        left, default mesh is the checkpoint's.

        `tenant` scopes the run's checkpoints to the tenant's namespace
        (`<checkpoint.dir>/<tenant>/`, per-tenant retention) and resume
        resolution to that tenant's rows. `trace`/`parent_op_id` stitch
        the run op under an EXISTING trace — the workload queue hands
        each dispatched run its entry op here, so a preempted tenant's
        whole life (queue wait → run → drain → resume) renders as ONE
        waterfall; when omitted, a resume stitches under the checkpoint's
        own op as before."""
        import jax

        from kubeoperator_tpu.parallel.mesh import MeshSpec
        from kubeoperator_tpu.workloads.checkpoint import restore_checkpoint
        from kubeoperator_tpu.workloads.harness import run_training
        from kubeoperator_tpu.workloads.partition import explain_rules
        from kubeoperator_tpu.workloads.step import (
            WORKLOAD_AXES,
            default_rules,
            train_state_shapes,
        )

        mode = str(mode or self.default_mode)
        if mode not in ("auto", "pjit", "shard_map"):
            raise ValidationError(
                f"workload mode {mode!r} not in (auto, pjit, shard_map)")
        if checkpoint and not resume:
            raise ValidationError(
                "--checkpoint names a resume source; pass resume=true "
                "with it")
        ckpt_row = (self._resolve_checkpoint(checkpoint, tenant=tenant)
                    if resume else None)

        if steps is None:
            if resume:
                # finish what the interrupted run had left (never less
                # than one step — a fully-finished checkpoint still
                # proves restore with one extra step)
                steps = max(ckpt_row.target_steps - ckpt_row.step, 1)
            else:
                steps = self.default_steps
        else:
            steps = int(steps)
        if steps < (1 if resume else 2):
            raise ValidationError(
                "workload train needs steps >= 2 — a single step has no "
                "loss pair for the descending-loss verdict"
                if not resume else
                "workload train --resume needs steps >= 1")

        devices = list(jax.devices())
        peak = self.peak_override or None
        envelope = None
        if plan:
            row = self.s.plans.get(plan)    # NotFoundError names the plan
            if not row.has_tpu():
                raise ValidationError(
                    f"plan {plan!r} has no TPU topology — `workload train` "
                    f"is the sharded TPU workload")
            topo = row.topology()
            if len(devices) != topo.jax_device_count:
                raise ValidationError(
                    f"plan {plan!r} ({topo.accelerator_type}) expects "
                    f"{topo.jax_device_count} devices, "
                    f"{len(devices)} visible")
            peak = peak or topo.generation.bf16_tflops_per_chip
            envelope = topo.theoretical_allreduce_busbw_gbps()

        mesh_text = str(mesh or self.default_mesh)
        if not mesh_text and resume and ckpt_row.mesh:
            # same mesh, same trajectory: resuming onto the checkpoint's
            # own layout is the loss-parity default; an explicit --mesh
            # (the degraded-mesh path) overrides it
            mesh_text = ",".join(f"{a}={n}"
                                 for a, n in ckpt_row.mesh.items())
        if mesh_text:
            spec = MeshSpec.parse(mesh_text, axis_names=WORKLOAD_AXES,
                                  n_devices=len(devices))
            missing = tuple((a, 1) for a in WORKLOAD_AXES
                            if a not in spec.axis_names)
            if missing:
                spec = MeshSpec(axes=spec.axes + missing)
        else:
            spec = MeshSpec(axes=(("data", len(devices)), ("fsdp", 1),
                                  ("tp", 1)))
        if spec.total_devices > len(devices):
            raise ValidationError(
                f"mesh {spec} needs {spec.total_devices} devices, "
                f"{len(devices)} visible")

        op_vars = {"plan": plan, "mesh": spec.describe(), "steps": steps,
                   "mode": mode}
        if tenant:
            op_vars["tenant"] = tenant
        if resume:
            op_vars["resumed_from"] = ckpt_row.id
            if not parent_op_id:
                parent_op_id = ckpt_row.op_id
                trace = trace or self._trace_of(ckpt_row.op_id)
        op = self.journal.open_scoped(
            WORKLOAD_TRAIN_KIND,
            vars=op_vars,
            message=(f"resume from checkpoint {ckpt_row.id[:8]} "
                     f"(step {ckpt_row.step}) on mesh {spec}" if resume
                     else f"sharded train on mesh {spec} "
                          f"({spec.total_devices} device(s))"),
            scope="workload", trace=trace, parent_op_id=parent_op_id,
        )
        log.info("workload op %s: mesh %s, %d steps, mode %s%s",
                 op.id, spec, steps, mode,
                 f", resuming {ckpt_row.id[:8]}" if resume else "")
        # log + event correlation for the whole run: every record this
        # thread emits (and every bus event stamped from the bound
        # context) names the tenant and the workload op — journal.close
        # clears the binding with the rest of the trace context
        from kubeoperator_tpu.observability import bind_trace

        bind_trace(trace_id=op.trace_id or None, op_id=op.id,
                   workload_op=op.id, tenant=tenant or None)
        # the run's private drain lane: keyed by the dispatch key so the
        # queue's targeted per-victim drains land on exactly this run
        run_key = parent_op_id or op.id
        rec = self._register_run(run_key, "train")
        try:
            mesh_obj = spec.build(devices[: spec.total_devices])
            state = None
            seed = 0
            if resume:
                t_restore = time.time()
                state, manifest = restore_checkpoint(
                    ckpt_row.dir, train_state_shapes())
                seed = int(manifest.get("seed", 0))
                self._record_windows(op, [{
                    "name": "checkpoint-restore", "start": t_restore,
                    "end": time.time(),
                    "attrs": {"checkpoint": ckpt_row.id,
                              "step": ckpt_row.step,
                              "bytes": manifest.get("total_bytes", 0)},
                }])
            target_planned = (ckpt_row.target_steps if resume else steps)

            def periodic_save(completed: int, live_state) -> None:
                # checkpoint.every_steps (ISSUE 12 satellite): a durable
                # mid-run save at the step boundary — same write path,
                # index row, and per-tenant retention as every other
                # checkpoint, so a crash between boundaries costs at
                # most every_steps steps
                if not self.ckpt_enabled:
                    return
                import jax
                import numpy as np

                t_save = time.time()
                host = jax.tree_util.tree_map(
                    lambda l: np.asarray(jax.device_get(l)), live_state)
                step_now = int(float(np.asarray(host["params"]["step"])))
                saved = self._write_checkpoint(
                    op, host, step=step_now,
                    target_steps=max(target_planned, step_now),
                    mesh=spec.describe(), seed=seed, losses=(),
                    tenant=tenant)
                self._record_windows(op, [{
                    "name": "checkpoint-save", "start": t_save,
                    "end": time.time(),
                    "attrs": {"checkpoint": saved["id"],
                              "step": step_now, "periodic": True,
                              "bytes": saved["bytes"]},
                }])

            # per-step telemetry (docs/observability.md "Events and live
            # telemetry"): one MetricSample per step boundary through the
            # op's tracer — only when tracing is on (the NullTracer path
            # must not pay a device_get per step)
            from kubeoperator_tpu.workloads.step import analytic_step_flops

            sampler = (_StepSampler(
                self.journal, op,
                flops_per_step=analytic_step_flops(mesh_obj),
                peak_tflops_per_chip=peak,
                devices=spec.total_devices, tenant=tenant,
                base_step=(ckpt_row.step if resume else 0),
            ) if (self.journal.events_enabled
                  and self.journal.tracer_for(op).enabled) else None)

            def on_step(completed: int, loss) -> bool:
                if sampler is not None:
                    sampler(completed, loss)
                return self._on_step(rec, completed, loss)

            run = run_training(mesh_obj, steps=steps, mode=mode, seed=seed,
                               state=state, on_step=on_step,
                               return_state=True,
                               checkpoint_every=self.ckpt_every,
                               on_checkpoint=(periodic_save
                                              if self.ckpt_every else None))
            final_state = run.pop("state", None)
            drained = bool(run.get("stopped_early"))
            windows = run.pop("windows", [])
            self._record_windows(op, windows)
            if run["mode"] == "pjit":
                run["rules"] = explain_rules(default_rules(),
                                             train_state_shapes())
            if peak:
                run["mfu_pct"] = round(
                    100.0 * run["model_tflops_per_s"]
                    / (peak * run["devices"]), 3)
                run["peak_tflops_per_chip"] = peak
            if envelope:
                run["ici_envelope_gbps"] = envelope
            target_steps = (max(ckpt_row.target_steps, run["end_step"])
                            if resume else steps)
            if self.ckpt_enabled:
                saved = self._save_checkpoint(
                    op, final_state, run, seed=seed,
                    target_steps=target_steps, tenant=tenant)
                run["checkpoint"] = saved
            if resume:
                run["resumed_from"] = ckpt_row.id
            if drained:
                run["drained"] = True
                run["drain_reason"] = rec["reason"]
            op.vars["result"] = run
            self.journal.save_vars(op)
            if drained:
                message = (
                    f"drained at step {run['end_step']}"
                    + (f"/{target_steps}" if target_steps else "")
                    + f" ({rec['reason']}); "
                    + (f"checkpoint {run['checkpoint']['id'][:8]} saved — "
                       f"resume with `koctl workload train --resume`"
                       if run.get("checkpoint") else
                       "checkpointing disabled — state lost"))
                # a drain is the platform doing its job, not a failure:
                # the op succeeds iff the partial losses were healthy
                self.journal.close(op, ok=bool(run["finite"]),
                                   message=message)
            else:
                self.journal.close(
                    op, ok=bool(run["ok"]),
                    message=(f"loss {run['losses'][0]} -> "
                             f"{run['losses'][-1]} "
                             f"in {run['steps']} steps "
                             f"({run['steps_per_s']} steps/s, "
                             f"{run['mode']})")
                    if run["ok"] else
                    (f"training unhealthy: finite={run['finite']} "
                     f"descending={run['descending']}"),
                )
        except KoError as e:
            self.journal.close(op, ok=False, message=e.message)
            raise
        except Exception as e:
            # jax/XLA failures surface as a failed journaled op, then as a
            # clean API error — never a raw traceback through the CLI
            self.journal.close(op, ok=False,
                               message=f"{type(e).__name__}: {e}")
            raise KoError(
                f"workload train failed ({type(e).__name__}): {e}") from e
        finally:
            self._unregister_run(run_key)
        return self.describe(self.repos.operations.get(op.id))

    def serve(self, mesh: str = "", requests: int | None = None,
              mode: str = "", checkpoint: str = "",
              slo_ms: float | None = None, tenant: str = "",
              trace: dict | None = None, parent_op_id: str = "") -> dict:
        """One serving session as a journaled operation (docs/
        workloads.md "Serving"): restore the named (or the tenant's
        latest) COMPLETE checkpoint — the checkpoint index is already a
        content-hashed, per-tenant model registry — hold the compiled
        forward fn resident through the serve compile seam, and answer
        `requests` batched requests, emitting one `request` metric
        sample per answer so `workload watch` shows the SLO live.

        A targeted `request_drain` stops the server at the next request
        boundary (the queue re-queues it exactly like a drained training
        victim — restore is the resume). A `request_degrade` directive
        re-shards it onto the surviving mesh WITHOUT stopping: reduced
        throughput, same answers — the slice-preemption contract."""
        import jax

        from kubeoperator_tpu.parallel.mesh import MeshSpec
        from kubeoperator_tpu.workloads.checkpoint import restore_checkpoint
        from kubeoperator_tpu.workloads.serve import run_serving
        from kubeoperator_tpu.workloads.step import (
            WORKLOAD_AXES,
            train_state_shapes,
        )

        mode = str(mode or self.default_mode)
        if mode not in ("auto", "pjit", "shard_map"):
            raise ValidationError(
                f"workload mode {mode!r} not in (auto, pjit, shard_map)")
        requests = (int(requests) if requests is not None
                    else self.serve_requests)
        if requests < 1:
            raise ValidationError("workload serve needs requests >= 1")
        slo = float(slo_ms) if slo_ms is not None else self.serve_slo_ms
        # serving starts FROM a model: no checkpoint, no server
        ckpt_row = self._resolve_checkpoint(checkpoint, tenant=tenant)

        devices = list(jax.devices())
        mesh_text = str(mesh or self.default_mesh)
        if not mesh_text and ckpt_row.mesh:
            mesh_text = ",".join(f"{a}={n}"
                                 for a, n in ckpt_row.mesh.items())
        if mesh_text:
            spec = MeshSpec.parse(mesh_text, axis_names=WORKLOAD_AXES,
                                  n_devices=len(devices))
            missing = tuple((a, 1) for a in WORKLOAD_AXES
                            if a not in spec.axis_names)
            if missing:
                spec = MeshSpec(axes=spec.axes + missing)
        else:
            spec = MeshSpec(axes=(("data", len(devices)), ("fsdp", 1),
                                  ("tp", 1)))
        if spec.total_devices > len(devices):
            raise ValidationError(
                f"mesh {spec} needs {spec.total_devices} devices, "
                f"{len(devices)} visible")

        op_vars = {"mesh": spec.describe(), "requests": requests,
                   "mode": mode, "slo_ms": slo,
                   "checkpoint_source": ckpt_row.id}
        if tenant:
            op_vars["tenant"] = tenant
        op = self.journal.open_scoped(
            WORKLOAD_SERVE_KIND, vars=op_vars,
            message=(f"serve checkpoint {ckpt_row.id[:8]} "
                     f"(step {ckpt_row.step}) on mesh {spec} "
                     f"({requests} request(s))"),
            scope="workload", trace=trace, parent_op_id=parent_op_id)
        log.info("workload serve op %s: checkpoint %s, mesh %s, "
                 "%d requests, slo %.1fms",
                 op.id, ckpt_row.id[:8], spec, requests, slo)
        from kubeoperator_tpu.observability import bind_trace

        bind_trace(trace_id=op.trace_id or None, op_id=op.id,
                   workload_op=op.id, tenant=tenant or None)
        run_key = parent_op_id or op.id
        rec = self._register_run(run_key, "serve")
        try:
            mesh_obj = spec.build(devices[: spec.total_devices])
            t_restore = time.time()
            state, manifest = restore_checkpoint(
                ckpt_row.dir, train_state_shapes())
            self._record_windows(op, [{
                "name": "checkpoint-restore", "start": t_restore,
                "end": time.time(),
                "attrs": {"checkpoint": ckpt_row.id,
                          "step": ckpt_row.step,
                          "bytes": manifest.get("total_bytes", 0)},
            }])

            sampling = (self.journal.events_enabled
                        and self.journal.tracer_for(op).enabled)

            def on_request(served: int, latency_s: float):
                if sampling:
                    from kubeoperator_tpu.models import MetricSample

                    self.journal.record_samples(op, [MetricSample(
                        op_id=op.id, step=int(served), kind="request",
                        tenant=tenant, step_s=round(float(latency_s), 6),
                        steps_per_s=(round(1.0 / latency_s, 3)
                                     if latency_s > 0 else 0.0),
                        attrs=({"slo_ms": slo} if slo else {}),
                    )])
                hook = self.request_hook
                directive = (hook(served, latency_s)
                             if hook is not None else None)
                # drain beats degrade: a stop directive is the queue
                # taking the whole gang back, not a layout change
                if rec["drain"].is_set():
                    return ("stop", rec["reason"])
                if directive:
                    return directive
                if rec["control"]:
                    return rec["control"].pop(0)
                return None

            run = run_serving(
                mesh_obj, params=state["params"], requests=requests,
                mode=mode, slo_ms=slo, on_request=on_request,
                seed=int(manifest.get("seed", 0)))
            self._record_windows(op, run.pop("windows", []))
            run["checkpoint_restored"] = ckpt_row.id
            op.vars["result"] = run
            self.journal.save_vars(op)
            if run.get("drained"):
                self.journal.close(
                    op, ok=bool(run["finite"]),
                    message=(f"drained after {run['served']}/{requests} "
                             f"request(s) ({run['drain_reason']}); "
                             f"re-dispatch restores checkpoint "
                             f"{ckpt_row.id[:8]}"))
            else:
                self.journal.close(
                    op, ok=bool(run["ok"]),
                    message=(f"served {run['served']} request(s) at "
                             f"{run['requests_per_s']} req/s "
                             f"(p95 {run['latency_p95_ms']}ms"
                             + (", degraded mesh" if run["degraded"]
                                else "")
                             + ")")
                    if run["ok"] else
                    f"serving unhealthy: finite={run['finite']}")
        except KoError as e:
            self.journal.close(op, ok=False, message=e.message)
            raise
        except Exception as e:
            self.journal.close(op, ok=False,
                               message=f"{type(e).__name__}: {e}")
            raise KoError(
                f"workload serve failed ({type(e).__name__}): {e}") from e
        finally:
            self._unregister_run(run_key)
        return self.describe(self.repos.operations.get(op.id))

    def sweep(self, steps: int | None = None, tenant: str = "",
              trace: dict | None = None, parent_op_id: str = "") -> dict:
        """The scaling-efficiency sweep (workloads/harness.run_sweep) as
        a JOURNALED operation — PR-9 residue closed: the sweep used to
        run ad-hoc (bench.py / perf_matrix), leaving no durable record.
        The workload queue submits it as a `scavenger`-class tenant, so
        it only runs when the whole pool is free and never displaces a
        paying workload; `trace`/`parent_op_id` stitch it under its
        queue entry like any dispatched run. Returns the op description
        with the per-axis rows in the result."""
        from kubeoperator_tpu.workloads.harness import run_sweep

        steps = int(steps) if steps is not None else self.default_steps
        if steps < 2:
            raise ValidationError(
                "workload sweep needs steps >= 2 — each swept mesh needs "
                "a loss pair for its health verdict")
        op_vars: dict = {"steps": steps}
        if tenant:
            op_vars["tenant"] = tenant
        op = self.journal.open_scoped(
            WORKLOAD_SWEEP_KIND, vars=op_vars,
            message=f"scaling-efficiency sweep ({steps} steps per mesh)",
            scope="workload", trace=trace, parent_op_id=parent_op_id)
        from kubeoperator_tpu.observability import bind_trace

        bind_trace(trace_id=op.trace_id or None, op_id=op.id,
                   workload_op=op.id, tenant=tenant or None)
        t0 = time.time()
        try:
            report = run_sweep(steps=steps, peak_tflops_per_chip=(
                self.peak_override or None))
            self._record_windows(op, [{
                "name": "sweep", "start": t0, "end": time.time(),
                "attrs": {"meshes": len(report["rows"]),
                          "devices": report["devices"]},
            }])
            op.vars["result"] = {
                "ok": report["ok"], "devices": report["devices"],
                "rows": report["rows"], "axes": report["axes"],
            }
            self.journal.save_vars(op)
            best = max((r["model_tflops_per_s"] for r in report["rows"]),
                       default=0.0)
            self.journal.close(
                op, ok=bool(report["ok"]),
                message=(f"swept {len(report['rows'])} meshes over "
                         f"{report['devices']} devices "
                         f"(best {best} model TFLOP/s)")
                if report["ok"] else "sweep produced unhealthy runs")
        except KoError as e:
            self.journal.close(op, ok=False, message=e.message)
            raise
        except Exception as e:
            self.journal.close(op, ok=False,
                               message=f"{type(e).__name__}: {e}")
            raise KoError(
                f"workload sweep failed ({type(e).__name__}): {e}") from e
        return self.describe(self.repos.operations.get(op.id))

    def _record_windows(self, op: Operation, windows: list) -> None:
        """Persist the run's named wall-clock windows (compile / steps /
        checkpoint-save/-restore) as WINDOW spans under the op root —
        the step-window layer of the trace tree (the shared
        `journal.record_windows` road, so cap/NullTracer behavior match
        every other window producer)."""
        self.journal.record_windows(op, windows)

    # ---- checkpoints ----
    def _trace_of(self, op_id: str) -> dict | None:
        """The trace-context wire shape stitching a resumed op under the
        original run's root span; None (fresh trace) when the original
        op or its trace is gone — resume must work even after prune."""
        try:
            orig = self.repos.operations.get(op_id)
        except NotFoundError:
            return None
        if not orig.trace_id:
            return None
        return {"trace_id": orig.trace_id, "parent_span_id": orig.id}

    def _resolve_checkpoint(self, ref: str = "",
                            tenant: str = "") -> Checkpoint:
        """A COMPLETE checkpoint by exact id, unique >=6-char prefix, or
        — with no ref — the newest one (the journal's op-ref resolution
        contract, applied to checkpoint rows). "Latest" is
        `CheckpointRepo.latest_complete` — the ONE query the slice pool
        and reconciler also use, so it can never mean different rows to
        different layers. A `tenant` scopes both forms to that tenant's
        namespace — tenant A's `--resume` must never pick up tenant B's
        state, however fresh."""
        scope = tenant if tenant else None
        if not ref:
            row = self.repos.checkpoints.latest_complete(tenant=scope)
            if row is None:
                label = f"(latest:{tenant})" if tenant else "(latest)"
                raise NotFoundError(kind="checkpoint", name=label)
            return row
        rows = self.repos.checkpoints.complete(tenant=scope)
        matches = [c for c in rows if c.id == ref]
        if not matches and len(ref) >= 6:
            matches = [c for c in rows if c.id.startswith(ref)]
        if len(matches) == 1:
            return matches[0]
        if len(matches) > 1:
            raise ValidationError(
                f"checkpoint ref {ref!r} is ambiguous "
                f"({len(matches)} matches)")
        raise NotFoundError(kind="checkpoint", name=ref)

    def _tenant_root(self, tenant: str) -> str:
        """The tenant's checkpoint namespace: `<checkpoint.dir>/<tenant>/`
        (the bare root for untenanted runs — pre-queue layouts keep
        working unchanged)."""
        return os.path.join(self.ckpt_dir, tenant) if tenant \
            else self.ckpt_dir

    def _write_checkpoint(self, op: Operation, host, *, step: int,
                          target_steps: int, mesh: dict, seed: int,
                          losses, tenant: str = "") -> dict:
        """Write + index one HOST TrainState checkpoint (manifest last)
        into the tenant's namespace and apply that tenant's retention.
        The one write path end-of-run, drain, and periodic saves share."""
        from kubeoperator_tpu.workloads.checkpoint import (
            manifest_sha,
            save_checkpoint,
        )

        manifest = save_checkpoint(
            self._tenant_root(tenant), host, step=step,
            target_steps=target_steps, mesh=mesh, op_id=op.id,
            losses=losses, seed=seed)
        row = Checkpoint(
            id=manifest["id"], op_id=op.id, tenant=tenant, step=step,
            target_steps=target_steps, dir=manifest["dir"],
            manifest_sha=manifest_sha(manifest), mesh=dict(mesh),
            total_bytes=int(manifest["total_bytes"]), status="complete")
        row.validate()
        self.repos.checkpoints.save(row)
        self._prune_checkpoints(keep_id=row.id, tenant=tenant)
        # checkpoint-save marker in the metric stream: `workload watch`
        # shows saves inline with the loss tail (NullTracer drops it)
        from kubeoperator_tpu.models import MetricSample

        self.journal.record_samples(op, [MetricSample(
            op_id=op.id, step=step, kind="checkpoint", tenant=tenant,
            attrs={"checkpoint": row.id, "bytes": row.total_bytes},
        )])
        return {"id": row.id, "step": row.step,
                "target_steps": target_steps, "dir": row.dir,
                "bytes": row.total_bytes}

    def _save_checkpoint(self, op: Operation, final_state, run: dict,
                         seed: int, target_steps: int,
                         tenant: str = "") -> dict | None:
        """Gather the final TrainState to host, write the sharded
        checkpoint (manifest last), index it, prune past retention, and
        persist the `checkpoint-save` window span. Returns the summary
        riding the run result, or None when there was no state."""
        import jax
        import numpy as np

        if final_state is None:
            return None
        t_save = time.time()
        host = jax.tree_util.tree_map(
            lambda l: np.asarray(jax.device_get(l)), final_state)
        saved = self._write_checkpoint(
            op, host, step=run["end_step"], target_steps=target_steps,
            mesh=run["mesh"], seed=seed, losses=run["losses"],
            tenant=tenant)
        self._record_windows(op, [{
            "name": "checkpoint-save", "start": t_save,
            "end": time.time(),
            "attrs": {"checkpoint": saved["id"], "step": saved["step"],
                      "bytes": saved["bytes"]},
        }])
        return saved

    def _prune_checkpoints(self, keep_id: str = "",
                           tenant: str = "") -> int:
        """Retention: keep the newest `checkpoint.keep` complete
        checkpoints OF THIS TENANT's namespace (the just-saved one
        always survives), delete the rest's directories and flip their
        rows to `pruned` — rows stay as the audit trail. Per-tenant
        scoping is the isolation contract: one tenant's churn can never
        prune another's rows."""
        rows = self.repos.checkpoints.complete(tenant=tenant)
        excess = len(rows) - self.ckpt_keep
        pruned = 0
        for row in rows:
            if excess <= 0:
                break
            if row.id == keep_id:
                continue
            shutil.rmtree(row.dir, ignore_errors=True)
            row.status = "pruned"
            self.repos.checkpoints.save(row)
            excess -= 1
            pruned += 1
        return pruned

    def sweep_torn(self) -> list[str]:
        """Boot hygiene (ControllerDeath mid-save): remove checkpoint
        directories without a complete manifest, and flip index rows
        whose directories vanished to `swept`. Called by the service
        container at boot, before anything tries to resume."""
        from kubeoperator_tpu.workloads.checkpoint import (
            MANIFEST_NAME,
            sweep_torn,
        )

        removed = sweep_torn(self.ckpt_dir)
        for row in self.repos.checkpoints.complete():
            if not os.path.isfile(os.path.join(row.dir, MANIFEST_NAME)):
                row.status = "swept"
                self.repos.checkpoints.save(row)
                log.warning("checkpoint %s swept: directory %s no longer "
                            "holds a manifest", row.id[:8], row.dir)
        return removed

    def checkpoints(self, tenant: str = "") -> list[dict]:
        """Checkpoint index rows, newest first — `koctl workload
        checkpoints [--tenant]` / GET /api/v1/workloads/checkpoints,
        the --resume picker and the drill's audit surface. `tenant`
        filters to one namespace; "" lists everything (the platform
        operator's view)."""
        rows = (self.repos.checkpoints.find(tenant=tenant) if tenant
                else self.repos.checkpoints.find())
        return [{
            "id": c.id, "op_id": c.op_id, "tenant": c.tenant,
            "step": c.step,
            "target_steps": c.target_steps, "mesh": c.mesh,
            "bytes": c.total_bytes, "status": c.status,
            "created_at": c.created_at,
        } for c in reversed(rows)]

    # ---- queries ----
    def resolve(self, op_ref: str = "") -> Operation:
        """A workload op — train, serve, or sweep — by exact id, unique
        id prefix, or — with no ref — the newest one (the shared journal
        resolution contract). Serve ops resolve here so `workload
        status|trace` work on them (the PR-12 sweep lesson)."""
        from kubeoperator_tpu.resilience.journal import resolve_op_ref

        return resolve_op_ref(
            self.repos,
            (WORKLOAD_TRAIN_KIND, WORKLOAD_SERVE_KIND,
             WORKLOAD_SWEEP_KIND),
            op_ref, label="workload operation")

    def describe(self, op: Operation) -> dict:
        v = op.vars
        result = v.get("result") or {}
        return {
            "id": op.id,
            "kind": op.kind,
            "status": op.status,
            "message": op.message,
            "tenant": v.get("tenant", ""),
            "plan": v.get("plan", ""),
            "mesh": v.get("mesh", {}),
            "steps": v.get("steps"),
            "mode": v.get("mode", ""),
            "result": v.get("result"),
            # checkpoint fields first-class in status/list JSON (ISSUE 11
            # satellite 1): what this run saved, what it resumed from,
            # and whether a preemption notice drained it
            "checkpoint": result.get("checkpoint"),
            "resumed_from": v.get("resumed_from")
            or result.get("resumed_from"),
            "drained": bool(result.get("drained")),
            "trace_id": op.trace_id,
            "created_at": op.created_at,
            "finished_at": op.finished_at or None,
        }

    def list_ops(self) -> list[dict]:
        ops = (self.repos.operations.find(kind=WORKLOAD_TRAIN_KIND)
               + self.repos.operations.find(kind=WORKLOAD_SERVE_KIND)
               + self.repos.operations.find(kind=WORKLOAD_SWEEP_KIND))
        ops.sort(key=lambda o: (o.created_at, o.id))
        return [self.describe(op) for op in reversed(ops)]

    def status(self, op_ref: str = "") -> dict:
        return self.describe(self.resolve(op_ref))

    def metrics(self, op_ref: str = "", after: int = 0) -> dict:
        """The op's per-step telemetry tail past cursor `after` (sqlite
        rowid, same contract as the event stream) — the data source for
        `GET /workloads/operations/{op}/metrics` and `koctl workload
        watch`. `live` says whether more samples may still arrive."""
        op = self.resolve(op_ref)
        rows, cursor = self.repos.metric_samples.since(op.id, int(after))
        return {
            "operation": op.id,
            "kind": op.kind,
            "status": op.status,
            "tenant": op.vars.get("tenant", ""),
            "cursor": cursor,
            "live": op.open,
            "samples": [{
                "id": rowid, "step": s.step, "kind": s.kind,
                "loss": s.loss, "step_s": s.step_s,
                "steps_per_s": s.steps_per_s, "tflops": s.tflops,
                "mfu_pct": s.mfu_pct, "attrs": dict(s.attrs),
                "ts": s.created_at,
            } for rowid, s in rows],
        }

    def trace(self, op_ref: str = "") -> dict:
        """The workload op's span tree: operation root → step windows —
        the `koctl workload trace` waterfall source."""
        from kubeoperator_tpu.observability import span_tree

        op = self.resolve(op_ref)
        return {
            "operation": op.id,
            "kind": op.kind,
            "status": op.status,
            "trace_id": op.trace_id,
            "tree": span_tree(self.journal.spans_of(op.id)),
        }
