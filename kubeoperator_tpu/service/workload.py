"""WorkloadService — sharded training as a first-class platform workload.

`koctl workload train --plan <plan> --mesh data=4,fsdp=2` lands here: the
(data, fsdp, tp) mesh is parsed through the declarative MeshSpec, the
partition-rule engine produces the layout (and its coverage report), and
the run executes as a JOURNALED operation — so a tenant training run
inherits everything cluster operations already have: the durable journal
row (PR 3), the persisted span tree with step-window spans under the op
root (`koctl workload trace` waterfalls, PR 5), and lease fencing in
multi-controller stacks (PR 8) for free, because every one of those
rides the journal the run writes through.

Scope: like a fleet rollout, a workload op belongs to the PLATFORM, not
to one cluster (`cluster_id == ""`, marker ``(workload)``); the lease
resource is the op's own id. Orphaned workload ops sweep to Interrupted
at boot with no resume path — re-running the workload IS the recovery
(training state is the tenant's checkpoint problem, not the journal's).

`--plan` pins the run to a deploy plan's TPU topology: the visible
device count must match the plan, and the plan's generation supplies the
MFU datasheet peak and ICI envelope context. Without a plan the run uses
whatever devices are visible (the tier-1 path: 8 host-platform CPU
devices).
"""

from __future__ import annotations

from kubeoperator_tpu.models import Operation
from kubeoperator_tpu.models.span import Span, SpanKind, SpanStatus
from kubeoperator_tpu.utils.errors import KoError, ValidationError
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("service.workload")

WORKLOAD_TRAIN_KIND = "workload-train"


def train_kwargs(body: dict) -> dict:
    """The body→`WorkloadService.train` translation BOTH transports share
    (REST handler and `LocalClient._dispatch`) — the behavioral half of
    the KO-X010 parity contract, same pattern as fleet's
    `upgrade_kwargs`."""
    from kubeoperator_tpu.fleet.planner import optional_int

    return {
        "plan": str(body.get("plan", "") or ""),
        "mesh": str(body.get("mesh", "") or ""),
        "steps": optional_int("steps", body.get("steps")),
        "mode": str(body.get("mode", "") or ""),
    }


class WorkloadService:
    def __init__(self, services) -> None:
        self.s = services
        self.repos = services.repos
        self.journal = services.journal
        cfg = services.config
        self.default_steps = int(cfg.get("workloads.steps", 4))
        self.default_mesh = str(cfg.get("workloads.mesh", "") or "")
        self.default_mode = str(cfg.get("workloads.mode", "auto"))
        self.peak_override = float(
            cfg.get("workloads.peak_tflops_per_chip", 0.0))

    # ---- the workload verb ----
    def train(self, plan: str = "", mesh: str = "", steps: int | None = None,
              mode: str = "") -> dict:
        """One sharded training run as a journaled operation; returns the
        op description including the run result and rule coverage."""
        import jax

        from kubeoperator_tpu.parallel.mesh import MeshSpec
        from kubeoperator_tpu.workloads.harness import run_training
        from kubeoperator_tpu.workloads.partition import explain_rules
        from kubeoperator_tpu.workloads.step import (
            WORKLOAD_AXES,
            default_rules,
            param_shapes,
        )

        steps = self.default_steps if steps is None else int(steps)
        if steps < 2:
            raise ValidationError(
                "workload train needs steps >= 2 — a single step has no "
                "loss pair for the descending-loss verdict")
        mode = str(mode or self.default_mode)
        if mode not in ("auto", "pjit", "shard_map"):
            raise ValidationError(
                f"workload mode {mode!r} not in (auto, pjit, shard_map)")

        devices = list(jax.devices())
        peak = self.peak_override or None
        envelope = None
        if plan:
            row = self.s.plans.get(plan)    # NotFoundError names the plan
            if not row.has_tpu():
                raise ValidationError(
                    f"plan {plan!r} has no TPU topology — `workload train` "
                    f"is the sharded TPU workload")
            topo = row.topology()
            if len(devices) != topo.jax_device_count:
                raise ValidationError(
                    f"plan {plan!r} ({topo.accelerator_type}) expects "
                    f"{topo.jax_device_count} devices, "
                    f"{len(devices)} visible")
            peak = peak or topo.generation.bf16_tflops_per_chip
            envelope = topo.theoretical_allreduce_busbw_gbps()

        mesh_text = str(mesh or self.default_mesh)
        if mesh_text:
            spec = MeshSpec.parse(mesh_text, axis_names=WORKLOAD_AXES,
                                  n_devices=len(devices))
            missing = tuple((a, 1) for a in WORKLOAD_AXES
                            if a not in spec.axis_names)
            if missing:
                spec = MeshSpec(axes=spec.axes + missing)
        else:
            spec = MeshSpec(axes=(("data", len(devices)), ("fsdp", 1),
                                  ("tp", 1)))
        if spec.total_devices > len(devices):
            raise ValidationError(
                f"mesh {spec} needs {spec.total_devices} devices, "
                f"{len(devices)} visible")

        op = self.journal.open_scoped(
            WORKLOAD_TRAIN_KIND,
            vars={"plan": plan, "mesh": spec.describe(), "steps": steps,
                  "mode": mode},
            message=f"sharded train on mesh {spec} "
                    f"({spec.total_devices} device(s))",
            scope="workload",
        )
        log.info("workload op %s: mesh %s, %d steps, mode %s",
                 op.id, spec, steps, mode)
        try:
            mesh_obj = spec.build(devices[: spec.total_devices])
            run = run_training(mesh_obj, steps=steps, mode=mode)
            windows = run.pop("windows", [])
            self._record_windows(op, windows)
            if run["mode"] == "pjit":
                run["rules"] = explain_rules(default_rules(), param_shapes())
            if peak:
                run["mfu_pct"] = round(
                    100.0 * run["model_tflops_per_s"]
                    / (peak * run["devices"]), 3)
                run["peak_tflops_per_chip"] = peak
            if envelope:
                run["ici_envelope_gbps"] = envelope
            op.vars["result"] = run
            self.journal.save_vars(op)
            self.journal.close(
                op, ok=bool(run["ok"]),
                message=(f"loss {run['losses'][0]} -> {run['losses'][-1]} "
                         f"in {run['steps']} steps "
                         f"({run['steps_per_s']} steps/s, {run['mode']})")
                if run["ok"] else
                (f"training unhealthy: finite={run['finite']} "
                 f"descending={run['descending']}"),
            )
        except KoError as e:
            self.journal.close(op, ok=False, message=e.message)
            raise
        except Exception as e:
            # jax/XLA failures surface as a failed journaled op, then as a
            # clean API error — never a raw traceback through the CLI
            self.journal.close(op, ok=False,
                               message=f"{type(e).__name__}: {e}")
            raise KoError(
                f"workload train failed ({type(e).__name__}): {e}") from e
        return self.describe(self.repos.operations.get(op.id))

    def _record_windows(self, op: Operation, windows: list) -> None:
        """Persist the run's named wall-clock windows (compile / steps) as
        WINDOW spans under the op root — the step-window layer of the
        trace tree. Ridden through the tracer's payload path (the same
        road executor-produced task spans take), so the span cap and
        NullTracer-off behavior apply unchanged."""
        tracer = self.journal.tracer_for(op)
        payloads = []
        for w in windows:
            payloads.append(Span(
                trace_id=op.trace_id, parent_id=op.id, op_id=op.id,
                cluster_id="", name=str(w.get("name", "window")),
                kind=SpanKind.WINDOW, status=SpanStatus.OK,
                started_at=float(w.get("start", 0.0)),
                finished_at=float(w.get("end", 0.0)),
                attrs=dict(w.get("attrs") or {}),
            ).to_dict())
        tracer.record_payload(payloads)
        tracer.flush()

    # ---- queries ----
    def resolve(self, op_ref: str = "") -> Operation:
        """A workload op by exact id, unique id prefix, or — with no
        ref — the newest one (the shared journal resolution contract)."""
        from kubeoperator_tpu.resilience.journal import resolve_op_ref

        return resolve_op_ref(self.repos, WORKLOAD_TRAIN_KIND, op_ref,
                              label="workload operation")

    def describe(self, op: Operation) -> dict:
        v = op.vars
        return {
            "id": op.id,
            "kind": op.kind,
            "status": op.status,
            "message": op.message,
            "plan": v.get("plan", ""),
            "mesh": v.get("mesh", {}),
            "steps": v.get("steps"),
            "mode": v.get("mode", ""),
            "result": v.get("result"),
            "trace_id": op.trace_id,
            "created_at": op.created_at,
            "finished_at": op.finished_at or None,
        }

    def list_ops(self) -> list[dict]:
        ops = self.repos.operations.find(kind=WORKLOAD_TRAIN_KIND)
        return [self.describe(op) for op in reversed(ops)]

    def status(self, op_ref: str = "") -> dict:
        return self.describe(self.resolve(op_ref))

    def trace(self, op_ref: str = "") -> dict:
        """The workload op's span tree: operation root → step windows —
        the `koctl workload trace` waterfall source."""
        from kubeoperator_tpu.observability import span_tree

        op = self.resolve(op_ref)
        return {
            "operation": op.id,
            "kind": op.kind,
            "status": op.status,
            "trace_id": op.trace_id,
            "tree": span_tree(self.journal.spans_of(op.id)),
        }
