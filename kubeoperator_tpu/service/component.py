"""ComponentService — per-cluster addon install/uninstall
(SURVEY.md §2.1 row 9): component CRUD → executor playbooks."""

from __future__ import annotations

from kubeoperator_tpu.adm import AdmContext, ClusterAdm
from kubeoperator_tpu.adm.engine import Phase
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import ClusterComponent
from kubeoperator_tpu.models.component import COMPONENT_CATALOG
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import NotFoundError, PhaseError


class ComponentService:
    def __init__(self, repos: Repositories, executor: Executor, events):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor)

    def catalog(self) -> dict:
        return {k: dict(v) for k, v in COMPONENT_CATALOG.items()}

    def list(self, cluster_name: str) -> list[ClusterComponent]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.components.find(cluster_id=cluster.id)

    def install(self, cluster_name: str, component_name: str,
                vars: dict | None = None) -> ClusterComponent:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        component = ClusterComponent(
            cluster_id=cluster.id, name=component_name,
            vars=vars or dict(COMPONENT_CATALOG.get(component_name, {}).get("vars", {})),
        )
        component.validate()
        existing = self.repos.components.find(cluster_id=cluster.id,
                                              name=component_name)
        if existing:
            component = existing[0]
            component.vars = vars or component.vars
        component.status = "Installing"
        self.repos.components.save(component)

        playbook = COMPONENT_CATALOG[component_name]["playbook"]
        ctx = self._context(cluster, component)
        try:
            self.adm.run(ctx, [Phase(f"component-{component_name}", playbook)])
        except PhaseError as e:
            component.status = "Failed"
            component.message = e.message
            self.repos.components.save(component)
            raise
        component.status = "Installed"
        component.message = ""
        self.repos.components.save(component)
        self.events.emit(cluster.id, "Normal", "ComponentInstalled",
                         f"{component_name} installed on {cluster_name}")
        return component

    def uninstall(self, cluster_name: str, component_name: str) -> None:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        existing = self.repos.components.find(cluster_id=cluster.id,
                                              name=component_name)
        if not existing:
            raise NotFoundError(kind="component", name=component_name)
        component = existing[0]
        component.status = "Uninstalled"
        self.repos.components.save(component)
        self.events.emit(cluster.id, "Normal", "ComponentUninstalled",
                         f"{component_name} removed from {cluster_name}")

    def _context(self, cluster, component: ClusterComponent) -> AdmContext:
        plan = (
            self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None
        )
        return AdmContext.for_cluster(self.repos, cluster, plan,
                                      dict(component.vars))
