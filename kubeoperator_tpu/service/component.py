"""ComponentService — per-cluster addon install/uninstall
(SURVEY.md §2.1 row 9): component CRUD → executor playbooks."""

from __future__ import annotations

import re

from kubeoperator_tpu.adm import AdmContext, ClusterAdm
from kubeoperator_tpu.adm.engine import Phase
from kubeoperator_tpu.executor import Executor
from kubeoperator_tpu.models import ClusterComponent
from kubeoperator_tpu.models.component import COMPONENT_CATALOG
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.errors import (
    NotFoundError,
    PhaseError,
    ValidationError,
)


# Component vars are rendered into role command lines (`--set k={{ v }}`).
# No whitespace or shell metacharacters means the ansible command module's
# shlex split can never turn one value into extra helm/kubectl arguments.
_INERT_VALUE_RE = re.compile(r"[A-Za-z0-9._:/@+=-]*")

# Catalog "template_only" vars (e.g. rook's device-filter regex, vSphere
# storage-policy names) never reach a command line, so regex metacharacters
# and spaces are fine — but they render inside a double-quoted YAML scalar
# in a kubectl-applied manifest, so anything that could break out of that
# scalar (quotes, backslash, newlines, braces) would be manifest injection
# and is rejected. Space is included: 'vSAN Default Storage Policy' is the
# de-facto default policy name on every greenfield vSphere cluster.
_TEMPLATE_SAFE_RE = re.compile(r"[A-Za-z0-9 ._:^$\[\]()|*+?/-]*")


def _check_conf_safe(vars: dict, origin: str) -> None:
    """For secret values that render ONLY into a quoted key = "value" conf
    template (csi-vsphere.conf): arbitrary passwords must pass — the only
    dangerous characters are the ones that escape the quoted value or add
    conf lines. Errors never echo the value (these are credentials)."""
    for key, value in vars.items():
        if isinstance(value, (bool, int, float)) or value is None:
            continue
        if not isinstance(value, str) or any(
            c in value for c in ('"', "\\", "\n", "\r")
        ):
            raise ValidationError(
                f"{origin} var {key!r} contains characters unsafe for the "
                f"connection config (quote/backslash/newline)"
            )


def _check_vars_inert(vars: dict, origin: str, redact: bool = False,
                      template_only: tuple = ()) -> None:
    """`redact=True` for secret-origin vars (backup-account keys): the error
    must name only the offending key, never echo the value into API
    responses or logs. Keys in `template_only` get the manifest-safety rule
    instead of the (stricter) shell-argument rule."""
    for key, value in vars.items():
        if isinstance(value, (bool, int, float)) or value is None:
            continue
        rule = _TEMPLATE_SAFE_RE if key in template_only else _INERT_VALUE_RE
        if not isinstance(value, str) or not rule.fullmatch(value):
            shown = "<redacted>" if redact else repr(value)
            kind = ("unsafe to render into a manifest"
                    if key in template_only else "non-argument-inert")
            raise ValidationError(
                f"{origin} var {key!r} has a {kind} value {shown}"
            )


class ComponentService:
    def __init__(self, repos: Repositories, executor: Executor, events,
                 retry_policy=None, retry_rng=None, journal=None,
                 scheduler=None):
        self.repos = repos
        self.events = events
        self.adm = ClusterAdm(executor, policy=retry_policy, rng=retry_rng,
                              scheduler=scheduler)
        from kubeoperator_tpu.resilience import default_journal

        self.journal = default_journal(repos, journal)

    def catalog(self) -> dict:
        return {k: dict(v) for k, v in COMPONENT_CATALOG.items()}

    def list(self, cluster_name: str) -> list[ClusterComponent]:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        return self.repos.components.find(cluster_id=cluster.id)

    def install(self, cluster_name: str, component_name: str,
                vars: dict | None = None) -> ClusterComponent:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("component install")
        entry = COMPONENT_CATALOG.get(component_name, {})
        existing = self.repos.components.find(cluster_id=cluster.id,
                                              name=component_name)
        if existing:
            component = existing[0]
            # a bare reinstall (no vars) must keep the customized vars, not
            # reset them to catalog defaults
            if vars is not None:
                component.vars = dict(vars)
        else:
            component = ClusterComponent(
                cluster_id=cluster.id, name=component_name,
                vars=dict(vars) if vars is not None else dict(
                    entry.get("vars", {})
                ),
            )
        # secret material (object-store keys) rides only in the phase's
        # extra-vars; it is never persisted on the component row, which the
        # API emits to view-role users
        secret_vars: dict = {}
        if component_name == "velero":
            component.vars, secret_vars = self._resolve_velero_vars(
                component.vars
            )
        elif component_name == "vsphere-csi":
            component.vars, secret_vars = self._resolve_vsphere_vars(
                cluster, component.vars
            )
        component.validate()
        _check_vars_inert(component.vars, component_name,
                          template_only=tuple(entry.get("template_only", ())))
        if component_name == "vsphere-csi":
            # vCenter credentials render only into the csi-vsphere.conf
            # template — the shell-argument rule would reject ordinary
            # passwords ('P4ss!word') and datacenter names with spaces
            _check_conf_safe(secret_vars, f"{component_name} vcenter")
        else:
            _check_vars_inert(secret_vars, f"{component_name} account",
                              redact=True)
        for required in entry.get("required", ()):
            if not component.vars.get(required):
                raise ValidationError(
                    f"{component_name} requires var {required!r}"
                )
        for var, allowed in entry.get("allowed", {}).items():
            value = component.vars.get(var)
            if value is not None and value not in allowed:
                raise ValidationError(
                    f"{component_name} var {var!r} must be one of "
                    f"{sorted(allowed)}, got {value!r}"
                )
        # bool-defaulted knobs must arrive as booleans: the string "false"
        # renders as false to helm (`| lower`) but TRUTHY to jinja `when:`
        # gates, and that split brain fails installs in ways only a live
        # cluster would surface (e.g. waiting on a daemonset helm never
        # deployed)
        for var, default in entry.get("vars", {}).items():
            value = component.vars.get(var)
            if isinstance(default, bool) and value is not None \
                    and not isinstance(value, bool):
                raise ValidationError(
                    f"{component_name} var {var!r} must be a boolean, "
                    f"got {value!r}"
                )
        component.status = "Installing"
        self.repos.components.save(component)

        playbook = entry["playbook"]
        ctx = self._context(cluster, component, secret_vars)
        op = self.journal.open(cluster, "component-install",
                               vars={"component": component_name})
        self.journal.attach(op, ctx)
        try:
            self.adm.run(ctx, [Phase(f"component-{component_name}", playbook)])
        except PhaseError as e:
            component.status = "Failed"
            component.message = e.message
            self.repos.components.save(component)
            self.journal.close(op, ok=False, message=e.message)
            raise
        self.journal.close(op, ok=True)
        component.status = "Installed"
        component.message = ""
        self.repos.components.save(component)
        self.events.emit(cluster.id, "Normal", "ComponentInstalled",
                         f"{component_name} installed on {cluster_name}")
        return component

    def uninstall(self, cluster_name: str, component_name: str) -> None:
        """Real teardown, not a status flip: runs component-uninstall.yml
        (or the catalog's "uninstall_playbook" override for components whose
        teardown is an ordered protocol, e.g. rook-ceph) with the declared
        helm releases / manifests / namespaces (models/component.py
        "uninstall"). Components without teardown data (tpu-runtime — see
        catalog rationale) skip straight to the status change."""
        cluster = self.repos.clusters.get_by_name(cluster_name)
        cluster.require_managed("component uninstall")
        existing = self.repos.components.find(cluster_id=cluster.id,
                                              name=component_name)
        if not existing:
            raise NotFoundError(kind="component", name=component_name)
        component = existing[0]
        entry = COMPONENT_CATALOG.get(component_name, {})
        teardown = entry.get("uninstall")
        if teardown:
            component.status = "Uninstalling"
            self.repos.components.save(component)
            ctx = self._context(cluster, component)
            op = self.journal.open(cluster, "component-uninstall",
                                   vars={"component": component_name})
            self.journal.attach(op, ctx)
            unlabel: list = [list(pair) for pair in teardown.get("unlabel", [])]
            if "unlabel_var" in teardown:
                # label applied to a VAR-driven namespace list at install
                # time (e.g. istio sidecar injection): resolve the installed
                # component's actual namespaces, not the catalog default
                var_name, label = teardown["unlabel_var"]
                namespaces = str(component.vars.get(
                    var_name, entry["vars"].get(var_name, ""),
                ))
                unlabel += [[ns, label] for ns in namespaces.split(":") if ns]
            ctx.extra_vars.update({
                "component_name": component_name,
                "uninstall_helm": list(teardown.get("helm", [])),
                "uninstall_manifests": list(teardown.get("manifests", [])),
                "uninstall_files": list(teardown.get("files", [])),
                "uninstall_unlabel": unlabel,
                "uninstall_secrets": [
                    list(pair) for pair in teardown.get("secrets", [])
                ],
                "uninstall_namespaces": list(teardown.get("namespaces", [])),
            })
            playbook = entry.get("uninstall_playbook",
                                 "component-uninstall.yml")
            try:
                self.adm.run(ctx, [Phase(f"uninstall-{component_name}",
                                         playbook)])
            except PhaseError as e:
                component.status = "UninstallFailed"
                component.message = e.message
                self.repos.components.save(component)
                self.journal.close(op, ok=False, message=e.message)
                self.events.emit(
                    cluster.id, "Warning", "ComponentUninstallFailed",
                    f"{component_name} teardown failed: {e.message}")
                raise
            self.journal.close(op, ok=True)
        component.status = "Uninstalled"
        component.message = ""
        self.repos.components.save(component)
        self.events.emit(cluster.id, "Normal", "ComponentUninstalled",
                         f"{component_name} removed from {cluster_name}")

    def _resolve_velero_vars(self, vars: dict) -> tuple[dict, dict]:
        """`account: <backup-account-name>` expands to the velero_* chart
        values from that BackupAccount (S3-compatible endpoints only).
        Returns (persistable vars, secret-only vars)."""
        vars = dict(vars)
        # `velero_account` is the persisted form, so a bare repair reinstall
        # (vars=None) can re-resolve the object-store keys instead of
        # overwriting the credentials file with empty strings
        account_name = vars.pop("account", "") or vars.get(
            "velero_account", ""
        )
        if not account_name:
            return vars, {}
        account = self.repos.backup_accounts.get_by_name(account_name)
        if account.type not in ("s3", "oss"):
            raise ValidationError(
                f"velero needs an s3/oss backup account, got {account.type}"
            )
        persisted = {
            "velero_account": account.name,
            "velero_bucket": account.bucket,
            "velero_s3_url": account.vars.get("endpoint", ""),
            "velero_region": account.vars.get("region", "minio"),
            **vars,
        }
        secrets = {
            "velero_access_key": account.vars.get("access_key", ""),
            "velero_secret_key": account.vars.get("secret_key", ""),
        }
        return persisted, secrets

    def _resolve_vsphere_vars(self, cluster, vars: dict) -> tuple[dict, dict]:
        """vCenter connection from the named (or the plan's own) vSphere
        region — same discipline as velero's backup account: credentials
        ride only the phase extra-vars, never the persisted component row.
        Returns (persistable vars, secret-only vars)."""
        vars = dict(vars)
        region = None
        region_name = vars.get("vcenter_region", "")
        if region_name:
            region = self.repos.regions.get_by_name(region_name)
        elif cluster.plan_id:
            plan = self.repos.plans.get(cluster.plan_id)
            if plan.provider == "vsphere":
                region = self.repos.regions.get(plan.region_id)
        if region is None:
            raise ValidationError(
                "vsphere-csi needs a vCenter: set vcenter_region to a "
                "vsphere region (plan-mode vSphere clusters default to "
                "their plan's region)"
            )
        if region.provider != "vsphere":
            raise ValidationError(
                f"region {region.name!r} is {region.provider}, "
                "vsphere-csi needs a vsphere region"
            )
        # fail at install, not 300s into a live-cluster rollout: a region
        # missing its connection vars renders [VirtualCenter ""] with an
        # empty password and dies in the CSI controller with an opaque
        # auth error
        missing = [k for k in ("vcenter_host", "vcenter_user",
                               "vcenter_password")
                   if not region.vars.get(k)]
        if missing:
            raise ValidationError(
                f"region {region.name!r} is missing {', '.join(missing)}; "
                "vsphere-csi cannot connect without them"
            )
        if not (vars.get("vsphere_datastore_url")
                or vars.get("vsphere_storage_policy")):
            raise ValidationError(
                "vsphere-csi needs vsphere_datastore_url or "
                "vsphere_storage_policy to place volumes"
            )
        persisted = {**vars, "vcenter_region": region.name}
        secrets = {
            "vcenter_host": region.vars.get("vcenter_host", ""),
            "vcenter_user": region.vars.get("vcenter_user", ""),
            "vcenter_password": region.vars.get("vcenter_password", ""),
            "vcenter_datacenter": region.vars.get("datacenter", "Datacenter"),
        }
        return persisted, secrets

    def _context(self, cluster, component: ClusterComponent,
                 secret_vars: dict | None = None) -> AdmContext:
        plan = (
            self.repos.plans.get(cluster.plan_id) if cluster.plan_id else None
        )
        return AdmContext.for_cluster(
            self.repos, cluster, plan,
            {**component.vars, **(secret_vars or {})},
        )
