"""Tenancy & auth services: users (local + LDAP-gated), sessions, projects,
RBAC (SURVEY.md §1 'Multi-tenancy & auth')."""

from __future__ import annotations

import secrets

from kubeoperator_tpu.models import Project, ProjectMember, Role, User
from kubeoperator_tpu.models.tenancy import hash_password, verify_password
from kubeoperator_tpu.repository import Repositories
from kubeoperator_tpu.utils.config import Config
from kubeoperator_tpu.utils.errors import (
    AuthError,
    ConflictError,
    ForbiddenError,
    NotFoundError,
    ValidationError,
)
from kubeoperator_tpu.utils.ids import now_ts


class UserService:
    def __init__(self, repos: Repositories, config: Config, ldap=None):
        self.repos = repos
        self.ldap = ldap  # LdapService; directory-verifies source='ldap' users
        self.session_ttl = float(config.get("server.session_ttl_s", 3600))
        self._sessions: dict[str, tuple[str, float]] = {}  # token -> (uid, exp)

    def create(self, name: str, password: str = "", email: str = "",
               is_admin: bool = False, source: str = "local") -> User:
        try:
            self.repos.users.get_by_name(name)
            raise ConflictError(kind="user", name=name)
        except NotFoundError:
            pass
        user = User(
            name=name, email=email, is_admin=is_admin, source=source,
            password_hash=hash_password(password) if password else "",
        )
        user.validate()
        return self.repos.users.save(user)

    def ensure_admin(self) -> User:
        """First-boot default admin (reference ships admin/kubeoperator@admin123
        [upstream — UNVERIFIED]; we generate and log a random password instead
        of shipping a fixed one)."""
        try:
            return self.repos.users.get_by_name("admin")
        except NotFoundError:
            password = secrets.token_urlsafe(12)
            user = self.create("admin", password=password, is_admin=True)
            from kubeoperator_tpu.utils.logging import get_logger

            get_logger("service.user").warning(
                "created default admin user; initial password: %s", password
            )
            return user

    def login(self, name: str, password: str) -> str:
        try:
            user = self.repos.users.get_by_name(name)
        except NotFoundError:
            raise AuthError()
        if not user.active:
            raise AuthError()
        if user.source == "ldap":
            if self.ldap is None or not self.ldap.enabled:
                raise AuthError(message="ldap authentication not configured")
            if not self.ldap.authenticate(name, password):
                raise AuthError()
        elif not verify_password(password, user.password_hash):
            raise AuthError()
        token = secrets.token_urlsafe(32)
        self._sessions[token] = (user.id, now_ts() + self.session_ttl)
        return token

    def logout(self, token: str) -> None:
        self._sessions.pop(token, None)

    def authenticate(self, token: str) -> User:
        entry = self._sessions.get(token)
        if entry is None:
            raise AuthError()
        uid, exp = entry
        if now_ts() > exp:
            del self._sessions[token]
            raise AuthError(message="session expired")
        return self.repos.users.get(uid)

    def change_password(self, name: str, old: str, new: str) -> None:
        user = self.repos.users.get_by_name(name)
        if not verify_password(old, user.password_hash):
            raise AuthError()
        if len(new) < 8:
            raise ValidationError("password must be >= 8 characters")
        user.password_hash = hash_password(new)
        self.repos.users.save(user)

    def list(self) -> list[User]:
        return self.repos.users.list()


class ProjectService:
    def __init__(self, repos: Repositories):
        self.repos = repos

    def create(self, name: str, description: str = "") -> Project:
        try:
            self.repos.projects.get_by_name(name)
            raise ConflictError(kind="project", name=name)
        except NotFoundError:
            pass
        project = Project(name=name, description=description)
        project.validate()
        return self.repos.projects.save(project)

    def list(self) -> list[Project]:
        return self.repos.projects.list()

    def get(self, name: str) -> Project:
        return self.repos.projects.get_by_name(name)

    def delete(self, name: str) -> None:
        project = self.get(name)
        if self.repos.clusters.find(project_id=project.id):
            raise ValidationError(
                f"project {name} still owns clusters; delete them first"
            )
        self.repos.projects.delete(project.id)

    def add_member(self, project_name: str, user_name: str,
                   role: str = Role.VIEWER.value) -> ProjectMember:
        project = self.get(project_name)
        user = self.repos.users.get_by_name(user_name)
        Role(role)
        existing = self.repos.project_members.find(
            project_id=project.id, user_id=user.id
        )
        member = existing[0] if existing else ProjectMember(
            project_id=project.id, user_id=user.id
        )
        member.role = role
        member.validate()
        return self.repos.project_members.save(member)

    def role_of(self, user: User, project_id: str) -> Role | None:
        if user.is_admin:
            return Role.ADMIN
        members = self.repos.project_members.find(
            project_id=project_id, user_id=user.id
        )
        return Role(members[0].role) if members else None

    def require(self, user: User, project_id: str, needed: Role) -> None:
        """RBAC gate used by the API layer (reference `pkg/permission`)."""
        role = self.role_of(user, project_id)
        if role is None or not role.allows(needed):
            raise ForbiddenError(action=f"{needed.value} on project")
