"""Web terminal into managed clusters (SURVEY.md §2.1 row 7: the reference
ships webkubectl — a gotty-based browser terminal with kubectl preloaded
against the cluster's kubeconfig).

Our equivalent: a PTY session manager (`TerminalManager`) the API layer
exposes as create/input/output/resize/close endpoints; output is polled or
SSE-streamed the same way task logs are, so the web console needs no
websocket stack.
"""

from kubeoperator_tpu.terminal.manager import TerminalManager, TerminalSession

__all__ = ["TerminalManager", "TerminalSession"]
