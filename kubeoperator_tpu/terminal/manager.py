"""PTY session manager behind the web terminal.

Each session is a real PTY running a kubectl-ready shell: the cluster's
kubeconfig is materialized to a 0600 temp file and exported as KUBECONFIG,
so `kubectl get nodes` works immediately (the reference's webkubectl does the
same inside its container). A reader thread drains the PTY master into a
bounded, seq-numbered chunk buffer the API polls/streams; sessions die on
idle timeout (reaped by the cron tick), process exit, or explicit close.

Trust model: the shell runs as the server process — inside the platform
bundle's server container in production, but always in the control-plane
trust domain. The API therefore gates opening to admins by default
(`terminal.allow_project_managers` widens it), and attach/input/output are
restricted to the opening user.
"""

from __future__ import annotations

import fcntl
import os
import pty
import signal
import struct
import subprocess
import tempfile
import termios
import threading
import time

from kubeoperator_tpu.utils.errors import NotFoundError, ValidationError
from kubeoperator_tpu.utils.ids import new_id, now_ts
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("terminal")

# Bounded scrollback per session: the web client keeps its own history; the
# server buffer only has to cover poll gaps. Policy is DROP-OLDEST with
# accounting — under a flooding child (`kubectl logs -f` on a busy pod, a
# stray `yes`) the buffer pins at MAX_BUFFERED_BYTES, the oldest chunks go,
# and read_since() reports how many chunks the caller missed so the client
# can show a gap marker instead of silently splicing output.
MAX_BUFFERED_CHUNKS = 2048
MAX_BUFFERED_BYTES = 1 << 20  # 1 MiB of scrollback per session, hard cap


class TerminalSession:
    def __init__(self, session_id: str, cluster_name: str, argv: list[str],
                 env: dict[str, str], kubeconfig_path: str = "",
                 user_id: str = "") -> None:
        self.id = session_id
        self.cluster_name = cluster_name
        self.user_id = user_id  # opener; only they (or an admin) may attach
        self.created_at = now_ts()
        self.last_active = now_ts()
        self._kubeconfig_path = kubeconfig_path
        self._lock = threading.Lock()
        self._chunks: list[tuple[int, bytes]] = []
        self._next_seq = 0
        self._first_seq = 0          # seq of the oldest RETAINED chunk
        self._buffered_bytes = 0
        self.dropped_chunks = 0      # lifetime drop count (observability)
        self._closed = False

        master, slave = pty.openpty()
        self._master = master
        try:
            self.process = subprocess.Popen(
                argv, stdin=slave, stdout=slave, stderr=slave,
                env=env, start_new_session=True, close_fds=True,
            )
        except OSError:
            os.close(master)
            raise
        finally:
            os.close(slave)
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    # ---- IO ----
    def _drain(self) -> None:
        while True:
            try:
                data = os.read(self._master, 4096)
            except OSError:
                break
            if not data:
                break
            with self._lock:
                self._chunks.append((self._next_seq, data))
                self._next_seq += 1
                self._buffered_bytes += len(data)
                # drop-oldest until back under BOTH caps; byte cap is the
                # one that binds under a flood (4KiB reads fill the chunk
                # cap 8x slower than the byte cap)
                while self._chunks and (
                    self._buffered_bytes > MAX_BUFFERED_BYTES
                    or len(self._chunks) > MAX_BUFFERED_CHUNKS
                ):
                    seq, dropped = self._chunks.pop(0)
                    self._buffered_bytes -= len(dropped)
                    self.dropped_chunks += 1
                    self._first_seq = seq + 1
        self.close()

    def write(self, data: bytes) -> None:
        # under the lock so close() can never shut the fd mid-write (a
        # reused fd number would silently receive the keystrokes)
        with self._lock:
            if self._closed:
                raise ValidationError("terminal session is closed")
            self.last_active = now_ts()
            os.write(self._master, data)

    def read_since(self, after_seq: int = -1) -> list[tuple[int, bytes]]:
        # last_active under the lock: write() updates it while holding it,
        # and a torn bare write here could push an in-use session past the
        # idle reaper's cutoff (ko-analyze KO-P008 guarded-by)
        with self._lock:
            self.last_active = now_ts()
            return [(s, d) for s, d in self._chunks if s > after_seq]

    def missed_since(self, after_seq: int = -1) -> int:
        """How many chunks between `after_seq` and the oldest retained one
        were dropped by the scrollback cap — the caller's output gap. 0 for
        a fresh session or a caller keeping up."""
        with self._lock:
            return self._missed_locked(after_seq)

    def _missed_locked(self, after_seq: int) -> int:
        if self._first_seq == 0:
            return 0
        return max(0, self._first_seq - (after_seq + 1))

    def read_with_gap(
        self, after_seq: int = -1
    ) -> tuple[int, list[tuple[int, bytes]]]:
        """(missed, chunks) under ONE lock hold — the poll/SSE handlers use
        this, not two separate calls, so a drop landing between a gap query
        and the read can never be spliced with an undercounted gap."""
        with self._lock:
            self.last_active = now_ts()
            return (
                self._missed_locked(after_seq),
                [(s, d) for s, d in self._chunks if s > after_seq],
            )

    @property
    def buffered_bytes(self) -> int:
        with self._lock:
            return self._buffered_bytes

    def resize(self, rows: int, cols: int) -> None:
        with self._lock:
            if self._closed:
                return
            winsize = struct.pack("HHHH", max(1, rows), max(1, cols), 0, 0)
            fcntl.ioctl(self._master, termios.TIOCSWINSZ, winsize)

    @property
    def alive(self) -> bool:
        return not self._closed and self.process.poll() is None

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self.process.poll() is None:
            try:
                os.killpg(self.process.pid, signal.SIGHUP)
            except ProcessLookupError:
                pass
            try:
                self.process.wait(timeout=2)
            except subprocess.TimeoutExpired:
                os.killpg(self.process.pid, signal.SIGKILL)
                self.process.wait(timeout=2)
        # fd close back under the lock: write()/resize() hold it, and _closed
        # is already set, so no thread can race the fd from here on
        with self._lock:
            try:
                os.close(self._master)
            except OSError:
                pass
        if self._kubeconfig_path:
            try:
                os.unlink(self._kubeconfig_path)
            except OSError:
                pass


class TerminalManager:
    """Owns all live sessions; enforces limits and idle reaping."""

    def __init__(self, repos, config) -> None:
        self.repos = repos
        self.shell = config.get("terminal.shell", "/bin/bash")
        self.max_sessions = int(config.get("terminal.max_sessions", 16))
        self.idle_timeout_s = float(config.get("terminal.idle_timeout_s", 900))
        self._sessions: dict[str, TerminalSession] = {}
        # lifetime drop count from CLOSED sessions: /metrics needs a
        # monotonic counter, and reap/close would otherwise erase history
        self._dropped_closed = 0
        self._lock = threading.Lock()

    def open(self, cluster_name: str, user_id: str = "") -> TerminalSession:
        cluster = self.repos.clusters.get_by_name(cluster_name)
        if not cluster.kubeconfig:
            raise ValidationError(
                f"cluster {cluster_name} has no kubeconfig; "
                "terminal requires a deployed cluster"
            )
        self.reap()
        fd, kc_path = tempfile.mkstemp(prefix="ko-term-", suffix=".conf")
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(cluster.kubeconfig)
        os.chmod(kc_path, 0o600)
        env = {
            "TERM": "xterm-256color",
            "PATH": os.environ.get("PATH", "/usr/bin:/bin"),
            "HOME": os.environ.get("HOME", "/tmp"),
            "KUBECONFIG": kc_path,
            "PS1": f"[{cluster_name}] \\w $ ",
        }
        # check + spawn + register under ONE lock hold so concurrent opens
        # cannot overshoot max_sessions; the spawn is fast (fork+exec)
        with self._lock:
            if len(self._sessions) >= self.max_sessions:
                os.unlink(kc_path)
                raise ValidationError(
                    f"terminal session limit ({self.max_sessions}) reached"
                )
            try:
                session = TerminalSession(
                    new_id(), cluster_name, [self.shell, "-i"], env, kc_path,
                    user_id=user_id,
                )
            except OSError as e:
                os.unlink(kc_path)  # never leave a kubeconfig behind
                raise ValidationError(
                    f"terminal shell {self.shell!r} failed to start: {e}"
                )
            self._sessions[session.id] = session
        log.info("terminal session %s opened into %s", session.id, cluster_name)
        return session

    def get(self, session_id: str) -> TerminalSession:
        with self._lock:
            session = self._sessions.get(session_id)
        if session is None:
            raise NotFoundError(kind="terminal", name=session_id)
        return session

    def close(self, session_id: str) -> None:
        with self._lock:
            session = self._sessions.pop(session_id, None)
            if session is not None:
                self._dropped_closed += session.dropped_chunks
        if session is not None:
            session.close()
            log.info("terminal session %s closed", session_id)

    def reap(self) -> int:
        """Close dead/idle sessions; returns how many were reaped."""
        cutoff = now_ts() - self.idle_timeout_s
        with self._lock:
            doomed = [
                sid for sid, s in self._sessions.items()
                if not s.alive or s.last_active < cutoff
            ]
        for sid in doomed:
            self.close(sid)
        return len(doomed)

    def list(self) -> list[dict]:
        self.reap()
        with self._lock:
            return [
                {
                    "id": s.id, "cluster": s.cluster_name,
                    "created_at": s.created_at, "alive": s.alive,
                }
                for s in self._sessions.values()
            ]

    def stats(self) -> dict:
        """Observability snapshot (platform /metrics), reaping first so
        dead/idle sessions are never counted live. dropped_chunks_total is
        monotonic: closed sessions' drops accumulate in _dropped_closed."""
        self.reap()
        with self._lock:
            live = list(self._sessions.values())
            return {
                "sessions": len(live),
                "dropped_chunks_total": self._dropped_closed + sum(
                    s.dropped_chunks for s in live
                ),
            }

    def shutdown(self) -> None:
        with self._lock:
            sessions = list(self._sessions.values())
            self._sessions.clear()
            self._dropped_closed += sum(s.dropped_chunks for s in sessions)
        for s in sessions:
            s.close()
