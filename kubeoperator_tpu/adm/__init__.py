"""ClusterAdm — the resumable phase state-machine (SURVEY.md §2.1 row 1c).

Pure orchestration: knows phase *dependencies* and *conditions*, delegates
every side effect to the executor/provisioner. One ClusterStatusCondition
row per phase; a failed operation re-enters at the unfinished frontier —
the first non-OK condition serially, every non-OK DAG node concurrently
(adm/dag.py, docs/scheduler.md).
"""

from kubeoperator_tpu.adm.dag import SchedulerConfig, scheduler_wiring
from kubeoperator_tpu.adm.engine import AdmContext, ClusterAdm, Phase
from kubeoperator_tpu.adm.phases import (
    backup_phases,
    cert_renew_phases,
    encryption_rotate_phases,
    etcd_maintenance_phases,
    create_phases,
    reset_phases,
    restore_phases,
    scale_down_phases,
    scale_up_phases,
    upgrade_phases,
)

__all__ = [
    "AdmContext", "ClusterAdm", "Phase", "SchedulerConfig",
    "scheduler_wiring",
    "create_phases", "upgrade_phases", "scale_up_phases", "scale_down_phases",
    "backup_phases", "restore_phases", "reset_phases", "cert_renew_phases",
    "encryption_rotate_phases", "etcd_maintenance_phases",
]
