"""The phase engine.

Parity model (SURVEY.md §3.1): for each phase — build inventory + extra-vars
from ClusterSpec, run the phase playbook through the executor, stream output
to the log sink, write ClusterStatusCondition(phase, OK|Failed); Failed halts
and a retry re-enters at the failed phase. Phase wall-clock spans land in the
conditions, so the create-to-Ready trace (BASELINE metric 1) falls out of the
condition rows for free (SURVEY.md §5.1).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

from kubeoperator_tpu.adm.dag import DagScheduler, SchedulerConfig, project_edges
from kubeoperator_tpu.executor.base import (
    Executor,
    FailureKind,
    TaskResult,
    TaskStatus,
    classify_result,
)
from kubeoperator_tpu.models import Cluster, Credential, Host, Node, Plan
from kubeoperator_tpu.models.cluster import ConditionStatus
from kubeoperator_tpu.models.span import SpanKind, SpanStatus
from kubeoperator_tpu.executor.inventory import build_inventory
from kubeoperator_tpu.observability.tracing import NullTracer, trace_context
from kubeoperator_tpu.resilience.policy import RetryPolicy
from kubeoperator_tpu.utils.errors import ExecutorError, PhaseError, ValidationError
from kubeoperator_tpu.utils.ids import now_ts
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("adm")


# link-local IP the nodelocaldns cache binds on every node; single source
# for the kubelet clusterDNS override and the DaemonSet manifest
NODELOCALDNS_IP = "169.254.20.10"


def _cluster_dns_ip(service_cidr: str) -> str:
    """kube-dns service ClusterIP: tenth address of the service range (the
    kubeadm convention). nodelocaldns forwards cache misses here.

    An unparseable CIDR raises instead of silently handing every node the
    10.96.0.10 default — a cluster deployed with DNS pointing into a range
    it doesn't own fails in ways far harder to diagnose than this error
    (ClusterSpec.validate normally rejects the spec first; this is the
    backstop for specs that bypassed it, e.g. hand-edited rows)."""
    import ipaddress

    try:
        net = ipaddress.ip_network(service_cidr, strict=False)
    except ValueError as e:
        raise ValidationError(
            f"service_cidr {service_cidr!r} is not a valid CIDR — refusing "
            f"to fall back to a default cluster DNS IP: {e}"
        )
    return str(net.network_address + 10)


def platform_vars_from_config(config) -> dict:
    """Derive the content-facing platform vars from process config."""
    url = str(config.get("registry.url", "http://127.0.0.1:8081"))
    # image references need a bare host:port (scheme-less); apt/yum/pip
    # repos need the full URL — content templates use whichever fits.
    host = url.split("://", 1)[-1].rstrip("/")
    return {"registry_url": url.rstrip("/"), "registry_host": host}


@dataclass(frozen=True)
class Phase:
    """One step of an operation.

    `after` turns the family from an ordered list into a dependency DAG
    (adm/dag.py, analyzer rule KO-X011): names of EARLIER-declared phases
    in the same family this one must wait for. A family with no edges
    keeps the historical strictly-serial schedule; declaration order is
    always a valid serial schedule either way (edges point backward)."""

    name: str                         # condition name, e.g. "etcd"
    playbook: str                     # content playbook file
    enabled: Callable[["AdmContext"], bool] = lambda ctx: True
    tags: tuple[str, ...] = ()
    limit_new_nodes: bool = False     # restrict to the joining nodes (scale-up)
    post: Callable[["AdmContext", TaskResult, list[str]], None] | None = None
    after: tuple[str, ...] = ()       # DAG edges (adm/dag.py)


@dataclass
class AdmContext:
    """Everything a phase run needs; assembled by the service layer."""

    cluster: Cluster
    nodes: list[Node]
    hosts_by_id: dict[str, Host]
    credentials_by_id: dict[str, Credential]
    plan: Plan | None = None
    new_node_names: set[str] = field(default_factory=set)
    extra_vars: dict = field(default_factory=dict)
    # sinks wired by the service layer
    log_sink: Callable[[str, str], None] = lambda task_id, line: None
    # batched form of log_sink (one store transaction per chunk instead of
    # per line — the create path's dominant IO cost); when left None the
    # engine falls back to per-line log_sink calls
    log_sink_many: Callable[[str, list], None] | None = None
    save_cluster: Callable[[Cluster], None] = lambda cluster: None
    # operation-journal progress hook (resilience/journal.py attach): the
    # engine reports every phase transition (name, Running|OK|Failed) so
    # the durable op row always knows how far the operation got. Under a
    # concurrent DAG run the Running reports carry the deterministic
    # composite label of everything in flight ("base+pki")
    on_phase: Callable[[str, str], None] = lambda name, status: None
    # DAG resume frontier ({"running": [...], "pending": [...]}) persisted
    # into the journal op's vars on every launch wave, so an interrupted
    # concurrent create says exactly which nodes were in flight
    on_frontier: Callable[[dict], None] = lambda frontier: None
    # span producer for this operation (journal.attach wires the real
    # Tracer; the default NullTracer keeps untraced runs at zero overhead)
    tracer: object = field(default_factory=NullTracer)

    @classmethod
    def for_cluster(cls, repos, cluster: Cluster, plan: Plan | None = None,
                    extra_vars: dict | None = None) -> "AdmContext":
        """Standard wiring every service uses: cluster fleet from the repos,
        log sink into task_logs, save_cluster persisting status."""
        return cls(
            cluster=cluster,
            nodes=repos.nodes.find(cluster_id=cluster.id),
            hosts_by_id={
                h.id: h for h in repos.hosts.find(cluster_id=cluster.id)
            },
            credentials_by_id={c.id: c for c in repos.credentials.list()},
            plan=plan,
            extra_vars=extra_vars or {},
            log_sink=lambda task_id, line: repos.task_logs.append(
                cluster.id, task_id, [line]
            ),
            log_sink_many=lambda task_id, lines: repos.task_logs.append(
                cluster.id, task_id, lines
            ),
            save_cluster=lambda c: repos.clusters.save(c),
        )

    def inventory(self) -> dict:
        return build_inventory(
            self.nodes, self.hosts_by_id, self.credentials_by_id,
            self.new_node_names or None,
        )

    def build_extra_vars(self) -> dict:
        """Tier-3 vars contract (SURVEY.md §5.6): ClusterSpec + plan TPU
        topology flattened for the content layer."""
        from kubeoperator_tpu.registry.manifest import COMPONENT_VERSIONS

        spec = self.cluster.spec
        ev: dict = {
            "cluster_name": self.cluster.name,
            "k8s_version": spec.k8s_version,
            "container_runtime": spec.runtime,
            "network_plugin": spec.cni,
            "ingress_controller": spec.ingress,
            "service_cidr": spec.service_cidr,
            "pod_cidr": spec.pod_cidr,
            "lb_mode": spec.lb_mode,
            "lb_endpoint": spec.lb_endpoint,
            "kube_proxy_mode": spec.kube_proxy_mode,
            "nodelocaldns_enabled": spec.nodelocaldns_enabled,
            "nodelocaldns_ip": NODELOCALDNS_IP,
            "cluster_dns_ip": _cluster_dns_ip(spec.service_cidr),
            "helm_enabled": spec.helm_enabled,
            "metrics_server_enabled": spec.metrics_server_enabled,
            "tpu_enabled": spec.tpu_enabled,
            "jobset_enabled": spec.jobset_enabled,
            # real executors must see an explicit False so `when: ko_simulation`
            # guards never hit an undefined var; SimulationExecutor overrides.
            "ko_simulation": False,
        }
        # component image tags pinned by the offline bundle manifest
        # (VERDICT r2 #4): the tag a template renders IS the tag the
        # registry serves
        ev.update({f"{k}_version": v for k, v in COMPONENT_VERSIONS.items()})
        if self.plan is not None and self.plan.has_tpu():
            topo = self.plan.topology()
            # simulated smoke bandwidth: 85% of the ICI envelope, so demo
            # clusters report a realistic number (the emitting task is gated
            # `when: ko_simulation`, so real runs never consume this).
            # Injected HERE, not per-service, so every smoke-bearing flow —
            # create, upgrade re-gate, slice scale, guided recovery — gets
            # the same value instead of silently recording 0.0.
            ev.setdefault("sim_smoke_gbps", round(
                0.85 * topo.theoretical_allreduce_busbw_gbps(), 1
            ))
            ev.update(
                tpu_type=topo.generation.name,
                tpu_accelerator_type=topo.accelerator_type,
                tpu_gcp_accelerator_type=topo.gcp_accelerator_type,
                tpu_slice_topology=topo.gcp_topology,
                tpu_num_slices=topo.num_slices,
                tpu_hosts_per_slice=topo.hosts_per_slice,
                tpu_chips_total=topo.total_chips,
                tpu_chips_per_host=topo.local_device_count,
                tpu_runtime_version=(
                    self.plan.tpu_runtime_version
                    or topo.generation.default_runtime_version
                ),
                smoke_test_gbps_threshold=spec.smoke_test_gbps_threshold,
            )
        ev.update(self.extra_vars)
        return ev


class _CompositeReporter:
    """on_phase wrapper for concurrent DAG runs: Running reports carry the
    deterministic composite label of everything in flight (sorted,
    '+'-joined — "base+pki"), terminal reports carry the finishing phase's
    own name. The journal op row therefore always names the full in-flight
    set, satisfying the KO-P007 discipline's "the durable record knows
    what was running" intent under concurrency."""

    def __init__(self, on_phase: Callable[[str, str], None]) -> None:
        self._on_phase = on_phase
        self._running: set[str] = set()
        self._lock = threading.Lock()

    def __call__(self, name: str, status_value: str) -> None:
        with self._lock:
            if status_value == ConditionStatus.RUNNING.value:
                self._running.add(name)
                label = "+".join(sorted(self._running))
            else:
                self._running.discard(name)
                label = name
            self._on_phase(label, status_value)


class _LogBatcher:
    """Chunked task-output sink: buffers streamed lines and lands them in
    batched store transactions (`scheduler.log_flush_lines` per commit)
    instead of one per line — the per-line commits were the create path's
    single largest cost. Falls back to per-line log_sink when the context
    wires no batch sink (hand-built AdmContexts in tests)."""

    def __init__(self, ctx: "AdmContext", task_id: str,
                 flush_lines: int) -> None:
        self._many = ctx.log_sink_many
        self._single = ctx.log_sink
        self._task_id = task_id
        self._n = max(int(flush_lines), 1)
        self._buf: list[str] = []

    def add(self, lines: list) -> None:
        if self._many is None:
            for line in lines:
                self._single(self._task_id, line)
            return
        self._buf.extend(lines)
        if len(self._buf) >= self._n:
            self.flush()

    def flush(self) -> None:
        if self._many is not None and self._buf:
            batch, self._buf = self._buf, []
            self._many(self._task_id, batch)


class ClusterAdm:
    """Runs a phase family against a context, resumably and — for
    TRANSIENT failures — self-healingly.

    `policy` governs in-phase auto-retry: a failed attempt classified
    TRANSIENT (unreachable hosts, deadlines, killed runner processes) is
    retried with exponential backoff up to `policy.max_attempts` before the
    phase halts; PERMANENT failures (genuinely failed tasks, post-hook
    vetoes) halt immediately for operator attention. `rng` (an explicitly
    seeded random.Random, or None) feeds backoff jitter; `sleep` is
    injectable so tests run the retry loop at full speed.

    `scheduler` (the `scheduler.*` config block) governs HOW the family
    executes: families that declare `Phase.after` edges run as a
    dependency DAG on a bounded worker pool when `max_concurrent_phases`
    allows; edge-less families — and everything when
    `max_concurrent_phases=1`, the direct-construction default — keep the
    historical strictly-serial loop (docs/scheduler.md)."""

    def __init__(
        self,
        executor: Executor,
        policy: RetryPolicy | None = None,
        rng=None,
        sleep: Callable[[float], None] = time.sleep,
        scheduler: SchedulerConfig | None = None,
    ) -> None:
        self.executor = executor
        self.policy = policy or RetryPolicy()
        self.rng = rng
        self._sleep = sleep
        self.scheduler = scheduler or SchedulerConfig()

    def run(self, ctx: AdmContext, phases: list[Phase]) -> None:
        """Execute the family; on failure raise PhaseError leaving the
        failed condition in place so the next run re-enters there.

        Resume semantics: if any of this operation's phases is unfinished
        (Unknown/Running/Failed), this is a retry — completed phases are
        skipped and execution re-enters at the unfinished frontier (the
        first unfinished phase serially; every unfinished DAG node
        concurrently). If all phases are OK (a *previous* run of this
        operation completed), the conditions are reset and the operation
        runs fresh — so a second scale-up or backup is never a silent
        no-op."""
        status = ctx.cluster.status
        active = [p for p in phases if p.enabled(ctx)]
        names = [p.name for p in active]

        existing = [status.condition(n) for n in names]
        all_ok = existing and all(
            c is not None and c.status == ConditionStatus.OK.value for c in existing
        )
        if all_ok:
            status.reset_conditions(names)

        # Pre-register conditions in order so the UI shows the full pipeline
        # up front (reference behavior: all conditions visible as Unknown).
        for p in active:
            if status.condition(p.name) is None:
                status.upsert_condition(p.name, ConditionStatus.UNKNOWN)
        ctx.save_cluster(ctx.cluster)

        if self.scheduler.max_concurrent_phases > 1 \
                and any(p.after for p in phases):
            self._run_dag(ctx, phases, active)
            return

        for p in active:
            cond = status.condition(p.name)
            if cond is not None and cond.status == ConditionStatus.OK.value:
                log.info("cluster %s: phase %s already OK, skipping",
                         ctx.cluster.name, p.name)
                continue
            self._run_phase(ctx, p)

    def _run_dag(self, ctx: AdmContext, family: list[Phase],
                 active: list[Phase]) -> None:
        """Concurrent path: schedule the active phases' dependency DAG on
        a bounded pool. Same observable contract as the serial loop —
        conditions, retries, spans (phase spans become siblings under the
        operation root), journal progress (composite labels), PhaseError
        on halt — plus the resume frontier persisted via ctx.on_frontier."""
        from kubeoperator_tpu.observability import bind_trace

        status = ctx.cluster.status
        completed = set()
        for p in active:
            cond = status.condition(p.name)
            if cond is not None and cond.status == ConditionStatus.OK.value:
                log.info("cluster %s: phase %s already OK, skipping",
                         ctx.cluster.name, p.name)
                completed.add(p.name)
        edges = project_edges(family, {p.name for p in active})
        # ONE lock per operation serializes status mutation + persist +
        # journal progress across this run's phase threads; phases on
        # OTHER clusters (other ctx) share nothing and stay unserialized
        lock = threading.Lock()
        report = _CompositeReporter(ctx.on_phase)
        tracer = ctx.tracer

        def run_one(phase: Phase) -> None:
            # phase worker threads are fresh: re-bind the log trace
            # context the service bound on the operation's own thread
            if getattr(tracer, "enabled", False):
                bind_trace(trace_id=tracer.trace_id or None,
                           op_id=getattr(tracer, "op_id", None),
                           cluster=ctx.cluster.name)
            self._run_phase(ctx, phase, lock=lock, report=report)

        def record_frontier(frontier: dict) -> None:
            # under the SAME per-operation lock as the workers' journal
            # progress writes: frontier saves and phase/status saves
            # mutate one Operation row, and an unserialized coordinator
            # write could persist a torn phase/phase_status pair
            with lock:
                ctx.on_frontier(frontier)

        try:
            DagScheduler(
                active, edges, self.scheduler.max_concurrent_phases,
                on_frontier=record_frontier,
            ).run(run_one, completed)
        except PhaseError as e:
            # siblings have settled (the scheduler drains before raising):
            # re-stamp the journal's phase pointer at the halting phase so
            # the durable record names the failure deterministically, not
            # whichever healthy sibling happened to finish last
            report(e.phase, ConditionStatus.FAILED.value)
            raise

    def _run_phase(self, ctx: AdmContext, phase: Phase,
                   lock: threading.Lock | None = None,
                   report: Callable[[str, str], None] | None = None) -> None:
        # `lock` serializes condition mutation + save + journal progress
        # against sibling DAG phases of the SAME operation (serial runs
        # pass none and pay one uncontended lock); `report` lets the DAG
        # path substitute composite-label progress reporting
        guard = lock if lock is not None else threading.Lock()
        report = report if report is not None else ctx.on_phase
        cluster = ctx.cluster
        status = cluster.status
        log.info("cluster %s: phase %s starting (%s)",
                 cluster.name, phase.name, phase.playbook)
        # the phase deadline bounds ALL attempts + backoff spans together;
        # upsert keeps started_at across in-phase retries, so the condition's
        # duration_s honestly includes the backoff the phase spent
        deadline = self.policy.deadline_from(now_ts())
        attempts = 0
        total_backoff = 0.0
        tracer = ctx.tracer
        # the phase span absorbs the condition's wall-clock role in the
        # trace tree: condition rows stay the resumability contract, the
        # span tree is the drill-down (docs/observability.md). A
        # ControllerDeath tears through WITHOUT closing spans — Running
        # spans next to the open journal op are the crash evidence.
        phase_span = tracer.start_span(
            phase.name, SpanKind.PHASE, parent_id=tracer.root_id,
            attrs={"playbook": phase.playbook},
        )

        def stamp(cond) -> None:
            cond.attempts = attempts
            cond.backoff_s = round(total_backoff, 3)

        while True:
            attempts += 1
            with guard:
                stamp(status.upsert_condition(
                    phase.name, ConditionStatus.RUNNING))
                ctx.save_cluster(cluster)
                report(phase.name, ConditionStatus.RUNNING.value)
            # retries are SIBLING attempt spans under the phase, each
            # carrying its FailureKind — the waterfall shows the retry
            # storm, not just the final outcome
            attempt_span = tracer.start_span(
                f"attempt-{attempts}", SpanKind.ATTEMPT,
                parent_id=phase_span.id, attrs={"attempt": attempts},
            )

            try:
                result, lines = self._attempt(
                    ctx, phase, deadline,
                    trace=(trace_context(tracer.trace_id, attempt_span.id)
                           if tracer.enabled else {}),
                )
                # task + host spans the executor built (possibly on the
                # far side of the runner RPC) land in the tree here
                tracer.record_payload(result.spans)
                if result.ok and phase.post is not None:
                    # post-hooks parse phase output (e.g. smoke-test GB/s)
                    # and may veto success by raising PhaseError — a
                    # deliberate judgment about output, never retried.
                    # Under the guard: hooks mutate shared cluster status
                    # (smoke history) a sibling phase may be persisting.
                    with guard:
                        phase.post(ctx, result, lines)
            except PhaseError as e:
                with guard:
                    cond = status.upsert_condition(
                        phase.name, ConditionStatus.FAILED, e.message)
                    stamp(cond)
                    cond.classification = FailureKind.PERMANENT.value
                    ctx.save_cluster(cluster)
                    report(phase.name, ConditionStatus.FAILED.value)
                tracer.end_span(attempt_span, SpanStatus.FAILED, {
                    "classification": FailureKind.PERMANENT.value,
                    "message": e.message})
                tracer.end_span(phase_span, SpanStatus.FAILED,
                                {"attempts": attempts})
                raise
            except Exception as e:
                # Anything else (post-hook bug, runner crash) must still
                # land the condition in Failed — a condition stuck at
                # Running would wedge resumability forever.
                with guard:
                    cond = status.upsert_condition(
                        phase.name, ConditionStatus.FAILED, str(e))
                    stamp(cond)
                    cond.classification = FailureKind.PERMANENT.value
                    ctx.save_cluster(cluster)
                    report(phase.name, ConditionStatus.FAILED.value)
                tracer.end_span(attempt_span, SpanStatus.FAILED, {
                    "classification": FailureKind.PERMANENT.value,
                    "message": str(e)})
                tracer.end_span(phase_span, SpanStatus.FAILED,
                                {"attempts": attempts})
                raise PhaseError(phase.name, str(e)) from e

            if result.ok:
                with guard:
                    cond = status.upsert_condition(
                        phase.name, ConditionStatus.OK)
                    stamp(cond)
                    cond.classification = ""
                    ctx.save_cluster(cluster)
                    report(phase.name, ConditionStatus.OK.value)
                tracer.end_span(attempt_span, SpanStatus.OK)
                tracer.end_span(phase_span, SpanStatus.OK,
                                {"attempts": attempts})
                log.info("cluster %s: phase %s OK (%.1fs, attempt %d)",
                         cluster.name, phase.name,
                         status.condition(phase.name).duration_s, attempts)
                return

            classification = (result.classification or classify_result(result)
                              or FailureKind.PERMANENT.value)
            tracer.end_span(attempt_span, SpanStatus.FAILED, {
                "classification": classification, "rc": result.rc,
                "message": result.message})
            retryable = (
                classification == FailureKind.TRANSIENT.value
                and attempts < self.policy.max_attempts
            )
            delay = self.policy.backoff_s(attempts, self.rng) if retryable else 0.0
            if retryable and deadline is not None \
                    and now_ts() + delay >= deadline:
                # no room left for another attempt inside the phase deadline
                retryable = False
            if not retryable:
                with guard:
                    cond = status.upsert_condition(
                        phase.name, ConditionStatus.FAILED, result.message)
                    stamp(cond)
                    cond.classification = classification
                    ctx.save_cluster(cluster)
                    report(phase.name, ConditionStatus.FAILED.value)
                tracer.end_span(phase_span, SpanStatus.FAILED, {
                    "attempts": attempts, "classification": classification})
                raise PhaseError(
                    phase.name,
                    f"{result.message} [{classification.lower()}, "
                    f"attempt {attempts}/{self.policy.max_attempts}]",
                )

            total_backoff += delay
            with guard:
                cond = status.upsert_condition(
                    phase.name, ConditionStatus.RUNNING,
                    f"attempt {attempts}/{self.policy.max_attempts} failed "
                    f"({classification.lower()}: {result.message}); retrying "
                    f"in {delay:.1f}s",
                )
                stamp(cond)
                cond.classification = classification
                ctx.save_cluster(cluster)
            log.warning(
                "cluster %s: phase %s attempt %d/%d failed (%s: %s); "
                "retrying in %.1fs", cluster.name, phase.name, attempts,
                self.policy.max_attempts, classification, result.message,
                delay,
            )
            if delay > 0:
                self._sleep(delay)

    def _attempt(
        self, ctx: AdmContext, phase: Phase, deadline: float | None,
        trace: dict | None = None,
    ) -> tuple[TaskResult, list[str]]:
        """One executor run of the phase playbook, streamed to the log sink.
        When the phase deadline expires mid-stream the task is cancelled
        cooperatively (kill hooks fire in process backends), so a hung
        playbook surfaces as a TRANSIENT deadline failure instead of
        wedging the deploy."""
        # executor-scoped platform vars (tier 1 → tier 3, SURVEY.md §5.6):
        # the service container stamps the configured offline-registry
        # address onto its executor, so every phase in that stack renders
        # content against the right registry — lowest precedence, and
        # scoped per Services instance (no process-global state).
        extra_vars = {
            **getattr(self.executor, "platform_vars", {}),
            **ctx.build_extra_vars(),
        }
        def transient_result(task_id: str, message: str) -> TaskResult:
            # executor-layer outage (runner process down/restarting): the
            # task never produced an honest result, so synthesize one the
            # retry loop can classify — this is what lets a deploy ride out
            # a runner restart instead of halting PERMANENT on an RPC error
            return TaskResult(
                task_id=task_id, status=TaskStatus.FAILED.value, rc=-1,
                message=message,
                classification=FailureKind.TRANSIENT.value,
            )

        if deadline is not None and deadline - now_ts() <= 0:
            # same TRANSIENT deadline label whether the budget ran out
            # between attempts or mid-stream — the loop's deadline check
            # turns this into the final halt
            return transient_result("", (
                f"phase {phase.name} deadline "
                f"({self.policy.phase_deadline_s:g}s) exhausted before "
                f"attempt could start")), []

        try:
            task_id = self.executor.run_playbook(
                phase.playbook,
                ctx.inventory(),
                extra_vars,
                tags=list(phase.tags),
                limit="new-workers" if phase.limit_new_nodes else "",
                # trace context rides the TaskSpec so the executor (local
                # or behind the runner RPC) mints task/host spans into
                # this attempt's subtree
                trace=dict(trace or {}),
            )
        except ExecutorError as e:
            return transient_result("", f"executor unavailable: {e.message}"), []
        lines: list[str] = []
        # pipelined sink: the stream is consumed in chunks and landed in
        # batched store transactions, so a slow log store never barriers
        # line-by-line on the create path (docs/scheduler.md)
        sink = _LogBatcher(ctx, task_id, self.scheduler.log_flush_lines)
        try:
            watch_kw = {}
            if deadline is not None:
                watch_kw["timeout_s"] = max(deadline - now_ts(), 0.001)
            for chunk in self.executor.watch_chunks(task_id, **watch_kw):
                lines.extend(chunk)
                sink.add(chunk)
            result = self.executor.result(task_id)
        except ExecutorError as e:
            # deadline hit OR the stream/boundary broke mid-task: reap the
            # task so nothing keeps running behind the deploy's back, then
            # hand the loop a TRANSIENT failure to classify/retry
            sink.flush()   # everything streamed so far is honest output
            if deadline is not None and now_ts() >= deadline:
                reason = (f"phase {phase.name} exceeded its "
                          f"{self.policy.phase_deadline_s:g}s deadline")
            else:
                reason = f"phase {phase.name} task stream failed: {e.message}"
            try:
                result = self.executor.cancel(task_id, reason=reason)
            except ExecutorError:
                result = transient_result(task_id, reason)
            if result.ok:
                # the task actually FINISHED ok — only the stream died. A
                # post-hook must never parse truncated output, so replay the
                # buffered stream (cheap: the task is done); if even that
                # fails, retry the attempt rather than judge partial lines.
                try:
                    replay = list(self.executor.watch(task_id, timeout_s=30.0))
                    sink.add(replay[len(lines):])   # sink only the tail
                    lines = replay
                except ExecutorError:
                    result = transient_result(task_id, reason)
            if not result.ok:
                ctx.log_sink(task_id, f"CANCELLED: {reason}")
        sink.flush()
        return result, lines
