"""BoundedPool — the deterministic bounded-concurrency coordinator the
phase-DAG scheduler (adm/dag.py) and the fleet wave engine
(fleet/engine.py) both run on.

Extracted from `DagScheduler` (ISSUE 13): the coordinator loop — launch
in caller-chosen deterministic order onto a bounded set of worker
threads, report every settle back on the coordinator thread, stop new
launches the moment the caller's policy says so, let running siblings
settle, and transport BaseExceptions (chaos `ControllerDeath`, lease
`StaleEpochError`) with crash semantics intact — is policy-free here.
What differs between consumers is POLICY, and that stays with them:

  * the DAG scheduler launches phases whose dependency sets are
    satisfied and halts on the first phase failure;
  * the fleet wave engine launches clusters in sorted-name order and
    halts when the live unavailability budget trips, a canary fails, or
    the operator signals pause/abort.

Contract:

  * `schedule(view)` runs on the coordinator thread, initially and after
    every settle, and returns the items to launch NOW (at most
    `view.free` of them; excess is an error). Returning nothing while
    workers run means "wait for a settle"; returning nothing with
    nothing running ends the run.
  * `work(item)` is the worker-thread body. Its return value (or the
    `Exception` it raised) is handed to `settle` — workers touch no
    shared state themselves.
  * `settle(item, result, error)` runs on the coordinator thread after
    each worker finishes, BEFORE the next `schedule` call — the verdict
    it records is what the next scheduling decision sees.
  * a `BaseException` from `work` is FATAL: `settle` is skipped for that
    item, no new launches happen, running siblings settle normally, and
    the first fatal re-raises from `run()` — the closest honest analogue
    of a crash, since a coordinator cannot SIGKILL a sibling thread.
"""

from __future__ import annotations

import threading
from typing import Callable

from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("adm.pool")


class PoolView:
    """What `schedule`/`on_turn` may consult: the free slot count and the
    items currently running (a snapshot — the coordinator owns the real
    set). Neither callback runs once a fatal has landed, so there is
    deliberately no fatal flag here."""

    __slots__ = ("free", "running")

    def __init__(self, free: int, running: list) -> None:
        self.free = free
        self.running = running


class BoundedPool:
    """One bounded worker pool run. Construct per use; `run` drives the
    coordinator loop to completion on the calling thread."""

    def __init__(self, max_concurrent: int,
                 thread_prefix: str = "pool") -> None:
        self.max_concurrent = max(int(max_concurrent), 1)
        self.thread_prefix = thread_prefix
        self._cv: threading.Condition | None = None   # live only in run()

    def kick(self) -> None:
        """Wake the coordinator so `schedule` is re-consulted NOW — for
        consumers whose scheduling inputs change from OUTSIDE the pool
        while every launched worker is still busy (a new workload-queue
        submission must not wait for the next settle to be considered).
        No-op before `run` starts or after it returns. Callers must not
        hold locks the coordinator's callbacks acquire."""
        cv = self._cv
        if cv is not None:
            with cv:
                cv.notify_all()

    def run(self, schedule: Callable, work: Callable,
            settle: Callable, on_turn: Callable | None = None) -> None:
        """Drive the pool until `schedule` yields nothing and every
        worker settled. `on_turn(view)` (optional) runs once per
        coordinator turn after launches — the frontier-persistence hook;
        suppressed once a fatal landed (a dead controller does no
        post-crash bookkeeping)."""
        cv = threading.Condition()
        self._cv = cv                       # expose for kick()
        running: list = []                  # items in flight, launch order
        inbox: list[tuple] = []             # (item, result, error) to settle
        fatal: list[BaseException] = []

        def worker(item) -> None:
            try:
                result = work(item)
            except Exception as e:
                with cv:
                    inbox.append((item, None, e))
                    cv.notify_all()
                return
            except BaseException as e:   # KO-P009: waived — ControllerDeath/
                # StaleEpochError transported to the coordinator, which
                # re-raises below with crash semantics intact
                with cv:
                    fatal.append(e)
                    running.remove(item)
                    cv.notify_all()
                return
            with cv:
                inbox.append((item, result, None))
                cv.notify_all()

        try:
            with cv:
                while True:
                    # settle everything that arrived, in arrival order,
                    # before the next scheduling decision — settle()
                    # verdicts feed it
                    while inbox:
                        item, result, error = inbox.pop(0)
                        running.remove(item)
                        settle(item, result, error)
                    free = self.max_concurrent - len(running)
                    launches = [] if fatal else list(schedule(
                        PoolView(free, list(running))))
                    if len(launches) > free:
                        raise RuntimeError(
                            f"{self.thread_prefix}: schedule returned "
                            f"{len(launches)} launches for {free} free slots")
                    for item in launches:
                        running.append(item)
                        label = getattr(item, "name", item)
                        threading.Thread(
                            target=worker, args=(item,), daemon=True,
                            name=f"{self.thread_prefix}-{label}",
                        ).start()
                    if on_turn is not None and not fatal:
                        on_turn(PoolView(self.max_concurrent - len(running),
                                         list(running)))
                    if not running and not inbox:
                        break
                    cv.wait()
        finally:
            self._cv = None

        if fatal:
            raise fatal[0]
