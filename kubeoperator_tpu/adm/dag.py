"""Phase dependency DAG + the bounded-concurrency scheduler behind it.

`Phase.after` (adm/engine.py) turns a phase family from an ordered list
into a dependency DAG: an edge `after=("pki",)` means the phase may not
start until `pki` has landed OK. The engine keeps its serial loop for
families that declare no edges (every non-create verb, until their DAGs
are declared) and for `scheduler.max_concurrent_phases=1`; for everything
else `ClusterAdm.run` hands the family to `DagScheduler`, which launches
ready phases onto a bounded thread pool in deterministic (declaration)
order.

Contract (validated here at run time and statically by analyzer rule
KO-X011):

  * every `after` edge resolves to a phase declared in the SAME family;
  * every edge points BACKWARD — a phase depends only on earlier-declared
    phases, so declaration order is always a valid topological order and
    the serial fallback executes the exact same graph;
  * names are unique, which (with the backward-edge rule) makes the
    ready-order a pure function of the declaration order: deterministic,
    whatever the thread interleaving did to completion timing.

Disabled phases (Phase.enabled false for this context) are spliced out of
the graph: an edge through a disabled phase is rewired to that phase's own
dependencies (an external-LB create drops `lb`, so `kube-master` falls
through to `lb`'s own `base` edge).

Failure semantics mirror the serial engine per ISSUE 7: a phase failure
(after its own in-phase retry budget is spent — `RetryPolicy` lives one
level down, in `_run_phase`) stops NEW launches but never cancels a
healthy sibling branch already running; when the pool drains, the
first-declared failure is re-raised. A BaseException (chaos
ControllerDeath) is re-raised with priority once in-flight siblings
settle — the engine cannot SIGKILL a sibling thread, so "settle" is the
closest honest analogue of a crash; the dying phase's condition stays
Running, which is exactly the crash evidence the boot reconciler sweeps.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from kubeoperator_tpu.utils.errors import ValidationError
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("adm.dag")


@dataclass(frozen=True)
class SchedulerConfig:
    """The `scheduler.*` config block (utils/config.py DEFAULTS)."""

    # phases running at once per operation; 1 = the historical serial loop
    max_concurrent_phases: int = 1
    # task-output lines buffered per sink flush (1 = commit every line,
    # the pre-DAG behavior; the batch is what keeps the log store off the
    # create path's critical path — docs/scheduler.md)
    log_flush_lines: int = 64

    @classmethod
    def from_config(cls, config, section: str = "scheduler") -> "SchedulerConfig":
        base = cls()
        return cls(
            max_concurrent_phases=int(config.get(
                f"{section}.max_concurrent_phases",
                base.max_concurrent_phases)),
            log_flush_lines=int(config.get(
                f"{section}.log_flush_lines", base.log_flush_lines)),
        )


def scheduler_wiring(config) -> "SchedulerConfig":
    """The ONE place the `scheduler.*` block becomes the SchedulerConfig
    every phase-running service hands its ClusterAdm — the concurrency
    posture cannot drift between entry points (same pattern as
    resilience.retry_wiring)."""
    return SchedulerConfig.from_config(config)


# ---------------------------------------------------------------- validate --
def validate_family(phases) -> list[str]:
    """Contract violations for one phase family (empty list = valid).

    Returns human-readable messages instead of raising so analyzer rule
    KO-X011 can turn each into a Finding; `build_edges` raises on the
    same set."""
    problems: list[str] = []
    seen: dict[str, int] = {}
    for i, p in enumerate(phases):
        if p.name in seen:
            problems.append(
                f"phase {p.name!r} is declared twice (positions "
                f"{seen[p.name]} and {i}) — duplicate names make the "
                f"ready-order ambiguous")
        else:
            seen[p.name] = i
    for i, p in enumerate(phases):
        for dep in p.after:
            if dep == p.name:
                problems.append(f"phase {p.name!r} depends on itself")
            elif dep not in seen:
                problems.append(
                    f"phase {p.name!r} has after-edge to {dep!r}, which is "
                    f"not declared in this family")
            elif seen[dep] > i:
                # backward-edges-only is the determinism AND acyclicity
                # guarantee: declaration order stays a topological order,
                # so the serial fallback and the DAG run the same graph
                problems.append(
                    f"phase {p.name!r} depends on later-declared {dep!r} — "
                    f"edges must point backward so declaration order "
                    f"remains a valid serial schedule")
    return problems


def build_edges(phases) -> dict[str, set[str]]:
    """Effective dependency sets for the ACTIVE phases of a family.

    `phases` is the enabled subset in declaration order; edges to phases
    missing from it (disabled for this context) are rewired transitively
    to the missing phase's own dependencies — callers pass the FULL family
    via each Phase's declared `after`, and disabled splicing happens here
    against the active name set. Raises ValidationError on a family that
    breaks the DAG contract."""
    problems = validate_family(phases)
    if problems:
        raise ValidationError(
            "phase family breaks the DAG contract (KO-X011): "
            + "; ".join(problems))
    return {p.name: set(p.after) for p in phases}


def project_edges(family, active_names: set[str]) -> dict[str, set[str]]:
    """Dependency sets restricted to `active_names`, splicing disabled
    phases out transitively. `family` is the FULL declared phase list (the
    splice needs the disabled phases' own edges)."""
    problems = validate_family(family)
    if problems:
        raise ValidationError(
            "phase family breaks the DAG contract (KO-X011): "
            + "; ".join(problems))
    declared = {p.name: tuple(p.after) for p in family}

    def resolve(dep: str, seen: frozenset) -> set[str]:
        if dep in active_names:
            return {dep}
        out: set[str] = set()
        for d in declared.get(dep, ()):
            if d not in seen:   # backward edges make cycles impossible;
                out |= resolve(d, seen | {dep})   # belt-and-braces anyway
        return out

    edges: dict[str, set[str]] = {}
    for p in family:
        if p.name not in active_names:
            continue
        deps: set[str] = set()
        for dep in declared[p.name]:
            deps |= resolve(dep, frozenset({p.name}))
        edges[p.name] = deps
    return edges


def _finish_times(durations: dict[str, float],
                  edges: dict[str, set[str]]) -> dict[str, float]:
    """Earliest-possible finish time per phase at measured durations:
    own duration plus the latest dependency finish (dependencies without
    a measured duration contribute nothing — they ran in another context
    or not at all)."""
    memo: dict[str, float] = {}

    def finish(name: str) -> float:
        if name not in memo:
            memo[name] = durations.get(name, 0.0) + max(
                (finish(d) for d in edges.get(name, ()) if d in durations),
                default=0.0)
        return memo[name]

    for name in durations:
        finish(name)
    return memo


def critical_lower_bound(durations: dict[str, float],
                         edges: dict[str, set[str]]) -> float:
    """Longest dependency chain through the DAG using measured per-phase
    durations — the wall-clock floor no scheduler can beat without
    changing the graph. `koctl trace --critical-path` quotes remaining
    headroom against this."""
    return max(_finish_times(durations, edges).values(), default=0.0)


def binding_chain(durations: dict[str, float],
                  edges: dict[str, set[str]]) -> list[str]:
    """The argmax dependency chain behind `critical_lower_bound`, in
    execution order — the phases an operator must shorten (or re-edge)
    to lower the DAG floor itself."""
    if not durations:
        return []
    memo = _finish_times(durations, edges)
    chain = [max(sorted(durations), key=memo.__getitem__)]
    while True:
        deps = [d for d in edges.get(chain[-1], ()) if d in durations]
        if not deps:
            break
        chain.append(max(sorted(deps), key=memo.__getitem__))
    chain.reverse()
    return chain


# ---------------------------------------------------------------- schedule --
class DagScheduler:
    """Runs one phase family's active DAG on a bounded worker pool.

    The coordinator loop (launch bookkeeping, settle transport, fatal
    semantics) is the shared `adm/pool.py BoundedPool`; this class keeps
    the DAG policy — ready = dependency set satisfied, launch order among
    simultaneously-ready phases is declaration order (the deterministic
    ready-order the KO-X011 contract promises), the first phase failure
    stops NEW launches but never cancels a healthy sibling branch."""

    def __init__(self, phases, edges: dict[str, set[str]],
                 max_concurrent: int,
                 on_frontier: Callable[[dict], None] | None = None) -> None:
        self.phases = list(phases)
        self.edges = edges
        self.max_concurrent = max(int(max_concurrent), 1)
        self.on_frontier = on_frontier or (lambda frontier: None)
        self._order = {p.name: i for i, p in enumerate(self.phases)}

    def run(self, run_phase: Callable, completed: set[str]) -> None:
        """Execute every phase not already in `completed` (resume skips
        OK conditions exactly like the serial loop). `run_phase(phase)`
        raises PhaseError when the phase halts after its retry budget."""
        from kubeoperator_tpu.adm.pool import BoundedPool

        done: set[str] = set(completed)
        pending = [p for p in self.phases if p.name not in done]
        failures: list[tuple[int, BaseException]] = []
        failed_names: set[str] = set()
        state = {"last_frontier": None}

        def schedule(view):
            if failures:
                return []
            ready = [p for p in pending
                     if self.edges.get(p.name, set()) <= done]
            launches = ready[:view.free]
            for p in launches:
                pending.remove(p)
            if not launches and not view.running and pending:
                # unreachable after validate_family; defensive so a
                # regression deadlocks loudly instead of silently
                raise ValidationError(
                    "phase DAG wedged: no phase ready, none running, "
                    + ", ".join(p.name for p in pending) + " pending")
            return launches

        def settle(phase, _result, error) -> None:
            if error is not None:
                failures.append((self._order[phase.name], error))
                failed_names.add(phase.name)
            else:
                done.add(phase.name)

        def on_turn(view) -> None:
            # the durable resume frontier: what is in flight plus what
            # the DAG still owes (never-launched AND failed nodes — a
            # retry re-enters both) — persisted (journal op vars) on
            # every change, so an interrupted op quotes the exact node
            # set a retry will re-enter. The pool suppresses this once a
            # fatal (ControllerDeath) landed: a dead controller does no
            # post-crash bookkeeping, so the pre-crash frontier with the
            # dying phase still listed as running IS the record.
            frontier = {
                "running": sorted(p.name for p in view.running),
                "pending": sorted(
                    {p.name for p in pending} | failed_names),
            }
            if frontier != state["last_frontier"]:
                state["last_frontier"] = frontier
                self.on_frontier(frontier)

        BoundedPool(self.max_concurrent, "adm-phase").run(
            schedule, run_phase, settle, on_turn=on_turn)

        if failures:
            failures.sort(key=lambda pair: pair[0])
            raise failures[0][1]
