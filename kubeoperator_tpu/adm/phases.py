"""Phase lists per operation (SURVEY.md §3.1/§3.3/§3.4/§3.5).

Create order mirrors the reference's numbered playbooks — prepare/base →
etcd → runtime → kube-master → kube-worker → network-plugin → post/addons —
with the north-star delta: the GPU phase is replaced by `tpu-runtime`
(libtpu env + TPU device plugin + JobSet) followed by `tpu-smoke-test`
(psum bus-bandwidth gate) [BASELINE].
"""

from __future__ import annotations

import json
import re

from kubeoperator_tpu.adm.engine import AdmContext, Phase
from kubeoperator_tpu.executor.base import TaskResult
from kubeoperator_tpu.utils.errors import PhaseError
from kubeoperator_tpu.utils.ids import now_ts

SMOKE_MARKER = "KO_TPU_SMOKE_RESULT"
UPGRADE_VERIFY_MARKER = "KO_TPU_UPGRADE_VERIFY"
RESTORE_VERIFY_MARKER = "KO_TPU_RESTORE_VERIFY"


def _tpu(ctx: AdmContext) -> bool:
    return ctx.cluster.spec.tpu_enabled


def _decode_escaped_fragment(frag: str) -> str:
    """`frag` is the tail of an ansible default-callback line, INSIDE a
    JSON-escaped string (`"msg": "KO_TPU_... {\\"gbps\\": ...}"...`).
    Cut at the first unescaped quote — the end of the containing string —
    then decode the JSON string escapes properly (handles `\\"`, `\\\\`,
    `\\n`, unicode escapes), instead of blind `replace('\\"', '"')`,
    which corrupted payloads containing literal backslash-quote sequences
    (VERDICT r4 weak #5 / ADVICE r4)."""
    out: list[str] = []
    i = 0
    while i < len(frag):
        ch = frag[i]
        if ch == '"':
            break  # closing quote of the containing "msg" string
        if ch == "\\" and i + 1 < len(frag):
            out.append(ch)
            out.append(frag[i + 1])
            i += 2
            continue
        out.append(ch)
        i += 1
    return json.loads('"' + "".join(out) + '"')


def parse_marker_json(marker: str, lines: list[str]) -> dict | None:
    """Find the last `<MARKER> {json}` line in phase output — the contract
    content roles use to hand structured results (smoke GB/s, verify
    attestations) back to the platform.

    Handles BOTH stdout shapes a debug-msg marker arrives in: the bare
    line (simulation executor, minimal callbacks, kubectl logs) and the
    real ansible default callback, which prints the whole msg JSON-escaped
    inside `"msg": "..."` — there the payload must be decoded as a JSON
    string fragment before parsing, or a marker containing embedded
    quotes/backslashes would corrupt (or fail a healthy cluster)."""
    decoder = json.JSONDecoder()
    # only whitespace may sit between the marker and its payload brace —
    # a later diagnostic line that merely MENTIONS the marker must not
    # shadow the genuine attestation (reversed scan, last match wins)
    pattern = re.compile(re.escape(marker) + r"\s*(\{)")
    for line in reversed(lines):
        m = pattern.search(line)
        if not m:
            continue
        frag = line[m.start(1):]
        # bare form: the first complete JSON object after the marker
        # (raw_decode tolerates trailing junk like the callback's `"}`)
        try:
            obj, _ = decoder.raw_decode(frag)
            if isinstance(obj, dict):
                return obj
        except json.JSONDecodeError:
            pass
        # escaped form: decode the containing JSON-string fragment first
        try:
            obj, _ = decoder.raw_decode(_decode_escaped_fragment(frag))
            if isinstance(obj, dict):
                return obj
        except (json.JSONDecodeError, ValueError):
            continue
    return None


def parse_smoke_result(lines: list[str]) -> dict | None:
    """The tpu-smoke-test role prints the psum Job's final log line, which
    the workload (ops/psum_smoke.py) emits as `KO_TPU_SMOKE_RESULT {json}`."""
    return parse_marker_json(SMOKE_MARKER, lines)


def smoke_post(ctx: AdmContext, result: TaskResult, lines: list[str]) -> None:
    """Gate Ready on the measured psum bandwidth (BASELINE metric 2)."""
    data = parse_smoke_result(lines)
    status = ctx.cluster.status
    if data is None:
        raise PhaseError("tpu-smoke-test", "no smoke-test result in job output")
    try:
        gbps = float(data.get("gbps") or 0.0)
        chips = int(data.get("chips") or 0)
    except (TypeError, ValueError):
        raise PhaseError(
            "tpu-smoke-test", f"malformed smoke-test result: {data!r}"
        )
    # honesty flag from the emitting task (`when: ko_simulation` path sets
    # it): carried through status + history so no surface can render a
    # fabricated GB/s as measured (VERDICT r3 weak #3)
    simulated = bool(data.get("simulated", False))
    status.smoke_gbps = gbps
    status.smoke_chips = chips
    status.smoke_simulated = simulated
    expected_chips = (
        ctx.plan.topology().total_chips if ctx.plan and ctx.plan.has_tpu() else 0
    )
    threshold = ctx.cluster.spec.smoke_test_gbps_threshold
    # record the measurement BEFORE gating: a failing run is exactly the
    # data point the console's trend should show. The pass flag also resets
    # here — a re-gate that fails must not leave a stale True from create.
    status.smoke_passed = False
    entry = {"ts": now_ts(), "gbps": gbps, "chips": chips, "passed": False,
             "simulated": simulated}
    status.smoke_history.append(entry)
    del status.smoke_history[:-20]   # bounded trend window
    if expected_chips and chips != expected_chips:
        raise PhaseError(
            "tpu-smoke-test",
            f"smoke test saw {chips} chips, expected {expected_chips}",
        )
    if threshold > 0 and gbps < threshold:
        raise PhaseError(
            "tpu-smoke-test",
            f"psum bandwidth {gbps:.1f} GB/s below threshold {threshold:.1f}",
        )
    status.smoke_passed = True
    entry["passed"] = True


def upgrade_verify_post(
    ctx: AdmContext, result: TaskResult, lines: list[str]
) -> None:
    """READY only on a parsed attestation, never on playbook rc alone
    (VERDICT r3 weak #6). The upgrade-verify role hands back the node
    versions it actually observed plus control-plane/dns/pod-sweep flags;
    the platform re-checks them against the target version and the node
    count it knows, so a verify-role regression that exits 0 without
    verifying cannot mark a half-upgraded cluster READY."""
    data = parse_marker_json(UPGRADE_VERIFY_MARKER, lines)
    if data is None:
        raise PhaseError(
            "upgrade-verify", "no verification attestation in phase output"
        )
    target = (ctx.extra_vars.get("target_k8s_version")
              or ctx.cluster.spec.k8s_version)
    if data.get("target") != target:
        raise PhaseError(
            "upgrade-verify",
            f"attestation is for {data.get('target')!r}, "
            f"this upgrade targets {target!r}",
        )
    versions = data.get("node_versions")
    if not isinstance(versions, list) or not versions:
        raise PhaseError(
            "upgrade-verify", f"malformed attestation: {data!r}"
        )
    expected = len(ctx.nodes)
    if expected and len(versions) != expected:
        raise PhaseError(
            "upgrade-verify",
            f"attestation covers {len(versions)} nodes, cluster has "
            f"{expected}",
        )
    stragglers = sorted({str(v) for v in versions if v != target})
    if stragglers:
        raise PhaseError(
            "upgrade-verify",
            f"nodes still at {', '.join(stragglers)} after upgrade to "
            f"{target}",
        )
    for key in ("nodes_ready", "apiserver_ok", "control_plane_ready",
                "coredns_ok", "kube_system_clean"):
        if data.get(key) is not True:
            raise PhaseError(
                "upgrade-verify", f"verification reports {key}=false"
            )


def restore_verify_post(
    ctx: AdmContext, result: TaskResult, lines: list[str]
) -> None:
    """A restore is not done when the playbook exits 0 — it is done when
    the cluster is demonstrably running THE RESTORED DATA (VERDICT r4
    weak #2). The restore-verify role hands back a restore-shaped
    attestation (no `target_k8s_version` here — restores have no version
    target, the CURRENT spec version is the contract):

      - `sentinel`: the `ko-tpu/backup-sentinel` etcd key, written by the
        backup role BEFORE the snapshot was taken with the snapshot's own
        file name. The platform compares it against the file it asked to
        restore — rc=0 with the wrong (or no) data cannot pass.
      - `k8s_version` as the apiserver reports it post-restart,
      - `node_count` as kubectl sees it,
      - `etcd_healthy` / `apiserver_ok` liveness flags.
    """
    data = parse_marker_json(RESTORE_VERIFY_MARKER, lines)
    if data is None:
        raise PhaseError(
            "restore-verify", "no restore attestation in phase output"
        )
    # Snapshots taken before sentinel support cannot contain the key —
    # BackupService grandfathers them via restore_expect_sentinel=False
    # (default True: an adm-level caller that doesn't say gets the full
    # gate, never a silent skip).
    if ctx.extra_vars.get("restore_expect_sentinel", True):
        expected_sentinel = str(ctx.extra_vars.get("backup_file_name", ""))
        got_sentinel = str(data.get("sentinel", ""))
        if not expected_sentinel or got_sentinel != expected_sentinel:
            raise PhaseError(
                "restore-verify",
                f"restored data carries sentinel {got_sentinel!r}, expected "
                f"{expected_sentinel!r} — the cluster is not running the "
                f"requested snapshot",
            )
    current = ctx.cluster.spec.k8s_version
    if data.get("k8s_version") != current:
        raise PhaseError(
            "restore-verify",
            f"apiserver reports {data.get('k8s_version')!r} after restore, "
            f"cluster spec is {current!r}",
        )
    try:
        node_count = int(data.get("node_count"))
    except (TypeError, ValueError):
        raise PhaseError(
            "restore-verify", f"malformed attestation: {data!r}"
        )
    # Deliberately NOT an equality check against the platform's current
    # node records: an etcd restore legitimately reverts Node objects to
    # backup-time topology (backup at 3 nodes, scaled to 4, restore → 3),
    # and worker kubelets may still be re-registering when the verify role
    # runs right after the control-plane restart. Zero nodes, though,
    # means the restored apiserver serves nothing — that is a failure.
    if node_count < 1:
        raise PhaseError(
            "restore-verify",
            "restored control plane serves no nodes",
        )
    for key in ("etcd_healthy", "apiserver_ok"):
        if data.get(key) is not True:
            raise PhaseError(
                "restore-verify", f"verification reports {key}=false"
            )


def create_phases() -> list[Phase]:
    """The one family with a declared dependency DAG (adm/dag.py,
    docs/scheduler.md): `after` edges encode the real data dependencies
    the span critical path exposed, so the scheduler can overlap
    prep-phase branches the serial list used to barrier on —

      * `pki` (cert generation on the deploy host) and the `pki`→`etcd`
        chain need no node prep, so they overlap `base`→`runtime`;
      * `lb` (haproxy/keepalived statics) needs only `base`;
      * `kube-master` is the join point: runtime + etcd + certs (+ lb
        when enabled — disabled phases splice out transitively);
      * `tpu-runtime` needs the CNI (`network`) but NOT `post` addons,
        so the TPU branch overlaps post/addons;
      * `tpu-smoke-test` gates on `tpu-runtime` alone (the device plugin
        + JobSet land there).

    Declaration order remains a valid serial schedule (edges point
    backward, enforced by KO-X011) — `scheduler.max_concurrent_phases=1`
    runs exactly the historical order."""
    return [
        Phase("base", "01-base.yml"),
        Phase("runtime", "02-runtime.yml", after=("base",)),
        Phase("pki", "03-pki.yml"),
        Phase("etcd", "05-etcd.yml", after=("pki",)),
        Phase("lb", "06-lb.yml",
              enabled=lambda ctx: ctx.cluster.spec.lb_mode == "internal",
              after=("base",)),
        Phase("kube-master", "07-kube-master.yml",
              after=("runtime", "etcd", "lb")),
        Phase("kube-worker", "08-kube-worker.yml", after=("kube-master",)),
        Phase("network", "09-network.yml", after=("kube-worker",)),
        Phase("post", "10-post.yml", after=("network",)),
        Phase("tpu-runtime", "16-tpu-runtime.yml", enabled=_tpu,
              after=("network",)),
        Phase("tpu-smoke-test", "17-tpu-smoke-test.yml", enabled=_tpu,
              post=smoke_post, after=("tpu-runtime",)),
    ]


def family_for_kind(kind: str) -> list[Phase] | None:
    """The phase family a journaled operation kind runs, for consumers
    reasoning about a FINISHED op's DAG from its kind alone (`koctl
    trace --critical-path` quotes the DAG lower bound against it). None
    for kinds whose family declares no `after` edges yet — their floor
    is the serial sum. Grow this map as more families gain DAGs."""
    if kind in ("create", "slice-scale"):
        return create_phases()
    return None


def upgrade_phases() -> list[Phase]:
    """Masters serially, then workers rolling (SURVEY.md §3.4). TPU
    clusters re-run the smoke gate at the end: the upgrade drained and
    restarted every kubelet, which can break device-plugin registration —
    an upgraded TPU cluster isn't done until the chips prove out again."""
    return [
        Phase("upgrade-prepare", "20-upgrade-prepare.yml"),
        Phase("upgrade-masters", "21-upgrade-masters.yml"),
        Phase("upgrade-workers", "22-upgrade-workers.yml"),
        Phase("upgrade-verify", "23-upgrade-verify.yml",
              post=upgrade_verify_post),
        Phase("upgrade-tpu-smoke", "17-tpu-smoke-test.yml", enabled=_tpu,
              post=smoke_post),
    ]


def scale_up_phases() -> list[Phase]:
    """Join phases limited to the new nodes only (SURVEY.md §3.3)."""
    return [
        Phase("scale-base", "01-base.yml", limit_new_nodes=True),
        Phase("scale-runtime", "02-runtime.yml", limit_new_nodes=True),
        Phase("scale-join", "08-kube-worker.yml", limit_new_nodes=True),
        Phase("scale-network", "09-network.yml", limit_new_nodes=True),
        Phase("scale-tpu-runtime", "16-tpu-runtime.yml", enabled=_tpu,
              limit_new_nodes=True),
    ]


def scale_down_phases() -> list[Phase]:
    return [
        Phase("drain", "30-drain-node.yml"),
        Phase("remove", "31-remove-node.yml"),
    ]


def backup_phases() -> list[Phase]:
    return [Phase("backup-etcd", "40-backup-etcd.yml")]


def restore_phases() -> list[Phase]:
    return [
        Phase("restore-etcd", "41-restore-etcd.yml"),
        Phase("restore-verify", "42-restore-verify.yml",
              post=restore_verify_post),
    ]


ETCD_MAINT_MARKER = "KO_TPU_ETCD_MAINT"


def etcd_maintenance_post(
    ctx: AdmContext, result: TaskResult, lines: list[str]
) -> None:
    """Maintenance is done when the WHOLE quorum answers healthy after the
    serial defrag — attested, not assumed from rc. The parsed sizes ride
    back to the service via ctx (the one channel a post hook has) so the
    completion event can say what the operation achieved."""
    data = parse_marker_json(ETCD_MAINT_MARKER, lines)
    if data is None:
        raise PhaseError(
            "etcd-maintenance", "no maintenance attestation in phase output"
        )
    if data.get("healthy") is not True:
        raise PhaseError(
            "etcd-maintenance",
            "quorum unhealthy after defrag — member(s) did not recover",
        )
    try:
        members = int(data.get("members"))
    except (TypeError, ValueError):
        raise PhaseError(
            "etcd-maintenance", f"malformed attestation: {data!r}"
        )
    expected = len([n for n in ctx.nodes if n.role == "master"])
    if expected and members != expected:
        raise PhaseError(
            "etcd-maintenance",
            f"attestation covers {members} members, cluster has {expected}",
        )
    ctx.extra_vars["__etcd_maint_result__"] = data


def etcd_maintenance_phases() -> list[Phase]:
    """Day-2 etcd defrag + alarm clear (playbook 26)."""
    return [Phase("etcd-maintenance", "26-etcd-maintenance.yml",
                  post=etcd_maintenance_post)]


def encryption_rotate_phases() -> list[Phase]:
    """Day-2 secrets-at-rest key rotation (content playbook 25; pairs with
    the pki role's initial secretbox generation)."""
    return [Phase("rotate-encryption-key", "25-rotate-encryption-key.yml")]


def cert_renew_phases() -> list[Phase]:
    """Day-2 PKI rotation (content playbook 24; pairs with the pki create
    phase). Re-fetches the rotated admin kubeconfig, so callers must refresh
    the stored cluster kubeconfig afterwards."""
    return [Phase("renew-certs", "24-renew-certs.yml")]


def reset_phases() -> list[Phase]:
    return [Phase("reset", "90-reset.yml")]
