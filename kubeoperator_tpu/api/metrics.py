"""Platform self-metrics: Prometheus text exposition, no dependencies.

VERDICT r3 missing #5 — the installer bundles grafana, but the platform
could not observe itself. This registry is the data source: process-lifetime
counters (HTTP requests, SSE consumers) updated by the API layer, plus
scrape-time collectors that read the live stack (clusters by phase, phase
durations from condition spans, executor task stats and queue depth,
terminal sessions, smoke bandwidth with its honesty label).

Exposition format reference: prometheus.io/docs/instrumenting/exposition_formats
(text format 0.0.4) — counters end in `_total`, label values escape
backslash/quote/newline, HELP/TYPE precede each family.
"""

from __future__ import annotations

import threading
import time


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(name: str, labels: dict | None, value) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
        )
        return f"{name}{{{inner}}} {value}"
    return f"{name} {value}"


class MetricsRegistry:
    """One per server process. Thread-safe: counters are touched from the
    request thread-pool; render() reads everything under the same lock."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._started = time.time()
        self._http: dict[tuple[str, int], int] = {}
        self._sse_consumers = 0

    # ---- process counters (hot path: O(1) under a short lock) ----
    def observe_http(self, method: str, status: int) -> None:
        key = (method, int(status))
        with self._lock:
            self._http[key] = self._http.get(key, 0) + 1

    def sse_started(self) -> None:
        with self._lock:
            self._sse_consumers += 1

    def sse_finished(self) -> None:
        with self._lock:
            self._sse_consumers -= 1

    # ---- exposition ----
    def render(self, services) -> str:
        from kubeoperator_tpu.version import __version__

        out: list[str] = []

        def family(name: str, mtype: str, help_: str, rows: list[str]):
            out.append(f"# HELP {name} {help_}")
            out.append(f"# TYPE {name} {mtype}")
            out.extend(rows)

        with self._lock:
            http = dict(self._http)
            sse = self._sse_consumers
        family("ko_tpu_info", "gauge", "Build info.",
               [_fmt("ko_tpu_info", {"version": __version__}, 1)])
        family("ko_tpu_uptime_seconds", "gauge",
               "Seconds since the server process started.",
               [_fmt("ko_tpu_uptime_seconds", None,
                     round(time.time() - self._started, 1))])
        family("ko_tpu_http_requests_total", "counter",
               "API requests served, by method and status code.",
               [_fmt("ko_tpu_http_requests_total",
                     {"method": m, "code": str(c)}, n)
                for (m, c), n in sorted(http.items())])
        family("ko_tpu_sse_consumers", "gauge",
               "Live SSE streams (log followers, event feeds, terminals).",
               [_fmt("ko_tpu_sse_consumers", None, sse)])

        # ---- scrape-time collectors over the live stack ----
        clusters = services.repos.clusters.list()
        by_phase: dict[str, int] = {}
        for c in clusters:
            by_phase[c.status.phase] = by_phase.get(c.status.phase, 0) + 1
        family("ko_tpu_clusters", "gauge", "Clusters by lifecycle phase.",
               [_fmt("ko_tpu_clusters", {"phase": p}, n)
                for p, n in sorted(by_phase.items())])

        # phase durations from condition spans (SURVEY §5.1: the native
        # trace) — sum+count per phase name lets dashboards chart averages
        span_sum: dict[str, float] = {}
        span_count: dict[str, int] = {}
        for c in clusters:
            for cond in c.status.conditions:
                if cond.finished_at and cond.started_at:
                    d = cond.finished_at - cond.started_at
                    span_sum[cond.name] = span_sum.get(cond.name, 0.0) + d
                    span_count[cond.name] = span_count.get(cond.name, 0) + 1
        # gauges, not counters: recomputed over RETAINED clusters each
        # scrape, so a cluster delete lowers them — rate()/increase()
        # would misread that as a counter reset. sum/count still chart
        # the average cleanly.
        family("ko_tpu_phase_duration_seconds_sum", "gauge",
               "Seconds spent in each adm phase, summed over retained "
               "clusters' condition spans.",
               [_fmt("ko_tpu_phase_duration_seconds_sum", {"phase": p},
                     round(s, 3))
                for p, s in sorted(span_sum.items())])
        family("ko_tpu_phase_duration_seconds_count", "gauge",
               "Completed phase runs recorded on retained clusters.",
               [_fmt("ko_tpu_phase_duration_seconds_count", {"phase": p}, n)
                for p, n in sorted(span_count.items())])

        try:
            stats = services.executor.task_stats()
        except Exception:
            # grpc backend with ko-runner down: scrape must not 500, and a
            # fabricated zero would read as "idle" — export up=0 and omit
            # the task families instead
            stats = None
        family("ko_tpu_executor_up", "gauge",
               "1 when the executor backend answers (for backend=grpc this "
               "is a liveness RPC against ko-runner).",
               [_fmt("ko_tpu_executor_up", None,
                     1 if stats is not None else 0)])
        if stats is not None:
            family("ko_tpu_executor_tasks_started_total", "counter",
                   "Playbook/adhoc tasks launched since process start.",
                   [_fmt("ko_tpu_executor_tasks_started_total", None,
                         stats["started_total"])])
            family("ko_tpu_executor_tasks", "gauge",
                   "Retained executor tasks by status (RUNNING = queue depth).",
                   [_fmt("ko_tpu_executor_tasks", {"status": s}, n)
                    for s, n in sorted(stats["by_status"].items())])

        term = services.terminals.stats()
        family("ko_tpu_terminal_sessions", "gauge",
               "Live web-terminal PTY sessions (reaped before counting).",
               [_fmt("ko_tpu_terminal_sessions", None, term["sessions"])])
        family("ko_tpu_terminal_dropped_chunks_total", "counter",
               "Output chunks dropped by the per-session scrollback cap "
               "(monotonic: closed sessions' drops are retained).",
               [_fmt("ko_tpu_terminal_dropped_chunks_total", None,
                     term["dropped_chunks_total"])])

        smoke_rows = []
        for c in clusters:
            if c.status.smoke_chips:
                smoke_rows.append(_fmt(
                    "ko_tpu_smoke_gbps",
                    {"cluster": c.name,
                     "simulated": str(bool(c.status.smoke_simulated)).lower()},
                    c.status.smoke_gbps,
                ))
        family("ko_tpu_smoke_gbps", "gauge",
               "Latest psum smoke bandwidth per TPU cluster (simulated "
               "label marks ko_simulation-fabricated values).", smoke_rows)

        return "\n".join(out) + "\n"
