"""Platform self-metrics: Prometheus text exposition, no dependencies.

VERDICT r3 missing #5 — the installer bundles grafana, but the platform
could not observe itself. This registry is the data source: process-lifetime
counters (HTTP requests, SSE consumers) updated by the API layer, plus
scrape-time collectors that read the live stack (clusters by phase,
phase/task duration HISTOGRAMS off the span store with trace-id exemplars,
journal ops by status, watchdog circuit state, executor task stats and
queue depth, terminal sessions, smoke bandwidth with its honesty label).

Exposition format reference: prometheus.io/docs/instrumenting/exposition_formats
(text format 0.0.4) — counters end in `_total`, label values escape
backslash/quote/newline, HELP/TYPE precede each family. When the scraper
negotiates OpenMetrics (`Accept: application/openmetrics-text`) the same
families render with OpenMetrics counter naming (`# TYPE x counter` +
`x_total` series), `# {trace_id="..."} v` exemplars on histogram buckets,
and the terminating `# EOF` — classic 0.0.4 output stays exemplar-free
because its parsers reject them.
"""

from __future__ import annotations

import threading
import time

# explicit histogram buckets for operation latencies: sub-second retries
# through half-hour phases; chosen once here so dashboards can hard-code
# the `le` grid
DURATION_BUCKETS_S = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, 600.0, 1800.0,
)

# The declared metric vocabulary: every family this registry exports, by
# its exposition name. Analyzer rule KO-P015 (metric-name discipline,
# docs/analysis.md) holds every LITERAL family name reaching the
# registry to this list — exactly, or as a sanctioned series suffix
# (_bucket/_sum/_count/_total) under a declared family — so a typo'd
# name cannot mint a family no dashboard, alert, or golden test selects.
METRIC_FAMILIES = (
    "ko_tpu_info",
    "ko_tpu_uptime_seconds",
    "ko_tpu_http_requests_total",
    "ko_tpu_sse_consumers",
    "ko_tpu_sse_sessions",
    "ko_tpu_sse_rows_delivered_total",
    "ko_tpu_sse_lag_seconds",
    "ko_tpu_clusters",
    "ko_tpu_phase_duration_seconds",
    "ko_tpu_task_duration_seconds",
    "ko_tpu_operations",
    "ko_tpu_fleet_waves",
    "ko_tpu_fleet_inflight_clusters",
    "ko_tpu_fleet_convergence",
    "ko_tpu_fleet_drifted_clusters",
    "ko_tpu_workload_queue",
    "ko_tpu_workload_queue_running",
    "ko_tpu_workload_queue_wait_seconds",
    "ko_tpu_events_total",
    "ko_tpu_workload_step_seconds",
    "ko_tpu_workload_request_seconds",
    "ko_tpu_workload_loss",
    "ko_tpu_db_statement_seconds",
    "ko_tpu_db_busy_retries_total",
    "ko_tpu_db_lock_wait_seconds_total",
    "ko_tpu_db_wal_bytes",
    "ko_tpu_db_tx_depth",
    "ko_tpu_watchdog_circuit_open",
    "ko_tpu_watchdog_budget_left",
    "ko_tpu_controller_leases",
    "ko_tpu_controller_lease_heartbeat_age_seconds",
    "ko_tpu_executor_up",
    "ko_tpu_executor_tasks_started_total",
    "ko_tpu_executor_tasks",
    "ko_tpu_terminal_sessions",
    "ko_tpu_terminal_dropped_chunks_total",
    "ko_tpu_smoke_gbps",
)


def _escape(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(name: str, labels: dict | None, value, exemplar: tuple | None = None,
         openmetrics: bool = False) -> str:
    if labels:
        inner = ",".join(
            f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
        )
        row = f"{name}{{{inner}}} {value}"
    else:
        row = f"{name} {value}"
    if exemplar is not None and openmetrics:
        trace_id, observed = exemplar
        row += f' # {{trace_id="{_escape(trace_id)}"}} {observed}'
    return row


class MetricsRegistry:
    """One per server process. Thread-safe: counters are touched from the
    request thread-pool; render() reads everything under the same lock."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._started = time.time()
        self._http: dict[tuple[str, int], int] = {}
        self._sse_consumers = 0
        # SSE session accounting by pump surface (logs / terminal /
        # events / metrics): live sessions, rows delivered, and the last
        # write-stall each surface saw — ROADMAP item 1's "thousands of
        # concurrent SSE sessions" acceptance needs this denominator
        self._sse_sessions: dict[str, int] = {}
        self._sse_rows: dict[str, int] = {}
        self._sse_lag_s: dict[str, float] = {}

    # ---- process counters (hot path: O(1) under a short lock) ----
    def observe_http(self, method: str, status: int) -> None:
        key = (method, int(status))
        with self._lock:
            self._http[key] = self._http.get(key, 0) + 1

    def sse_started(self, surface: str = "") -> None:
        with self._lock:
            self._sse_consumers += 1
            if surface:
                self._sse_sessions[surface] = \
                    self._sse_sessions.get(surface, 0) + 1

    def sse_finished(self, surface: str = "") -> None:
        # clamped at 0: a double-finish (e.g. an exception path running a
        # finally twice, or a finish with no matching start) must read as
        # "zero consumers", never as a negative gauge that poisons every
        # dashboard sum it joins
        with self._lock:
            self._sse_consumers = max(self._sse_consumers - 1, 0)
            if surface:
                self._sse_sessions[surface] = max(
                    self._sse_sessions.get(surface, 0) - 1, 0)

    def sse_rows_delivered(self, surface: str, n: int) -> None:
        """`n` frames written to one session's socket (counted per write
        batch, not per session close, so a long follow shows throughput
        while it is still running)."""
        if n <= 0:
            return
        with self._lock:
            self._sse_rows[surface] = self._sse_rows.get(surface, 0) + n

    def sse_write_lag(self, surface: str, seconds: float) -> None:
        """Wall-clock one frame batch spent blocked in socket writes —
        the slow-consumer signal: a reader that cannot drain its stream
        backs this up long before frames drop."""
        with self._lock:
            self._sse_lag_s[surface] = seconds

    # ---- exposition ----
    def render(self, services, openmetrics: bool = False) -> str:
        from kubeoperator_tpu.version import __version__

        out: list[str] = []

        def family(name: str, mtype: str, help_: str, rows: list[str]):
            # OpenMetrics names a counter family WITHOUT the _total suffix
            # (the series keep it); classic 0.0.4 uses the suffixed name
            header = name
            if openmetrics and mtype == "counter" and name.endswith("_total"):
                header = name[: -len("_total")]
            out.append(f"# HELP {header} {help_}")
            out.append(f"# TYPE {header} {mtype}")
            out.extend(rows)

        def histogram(name: str, help_: str, label: str,
                      rows: list[tuple]) -> None:
            """One histogram family from (label_value, duration_s,
            trace_id) observations: cumulative explicit buckets + _sum +
            _count per label value, each bucket carrying the LAST
            observation that landed in it as its trace-id exemplar."""
            by_label: dict[str, list[tuple]] = {}
            for value, duration, trace_id in rows:
                by_label.setdefault(value, []).append((duration, trace_id))
            lines: list[str] = []
            for value in sorted(by_label):
                observations = by_label[value]
                lower = float("-inf")
                for le in (*DURATION_BUCKETS_S, float("inf")):
                    cumulative = sum(1 for d, _ in observations if d <= le)
                    # the exemplar is the LAST observation landing in this
                    # bucket's own (lower, le] band — `le` rows themselves
                    # stay cumulative, per the histogram contract
                    in_band = [(d, t) for d, t in observations
                               if lower < d <= le and t]
                    exemplar = ((in_band[-1][1], round(in_band[-1][0], 6))
                                if in_band else None)
                    le_text = "+Inf" if le == float("inf") else f"{le:g}"
                    lines.append(_fmt(
                        f"{name}_bucket", {label: value, "le": le_text},
                        cumulative, exemplar, openmetrics))
                    lower = le
                lines.append(_fmt(
                    f"{name}_sum", {label: value},
                    round(sum(d for d, _ in observations), 6)))
                lines.append(_fmt(
                    f"{name}_count", {label: value}, len(observations)))
            family(name, "histogram", help_, lines)

        with self._lock:
            http = dict(self._http)
            sse = self._sse_consumers
            sse_sessions = dict(self._sse_sessions)
            sse_rows = dict(self._sse_rows)
            sse_lag = dict(self._sse_lag_s)
        family("ko_tpu_info", "gauge", "Build info.",
               [_fmt("ko_tpu_info", {"version": __version__}, 1)])
        family("ko_tpu_uptime_seconds", "gauge",
               "Seconds since the server process started.",
               [_fmt("ko_tpu_uptime_seconds", None,
                     round(time.time() - self._started, 1))])
        family("ko_tpu_http_requests_total", "counter",
               "API requests served, by method and status code.",
               [_fmt("ko_tpu_http_requests_total",
                     {"method": m, "code": str(c)}, n)
                for (m, c), n in sorted(http.items())])
        family("ko_tpu_sse_consumers", "gauge",
               "Live SSE streams (log followers, event feeds, terminals).",
               [_fmt("ko_tpu_sse_consumers", None, sse)])
        # per-surface session accounting (docs/observability.md
        # "Control-plane DB telemetry"): which pump carries the fanout
        family("ko_tpu_sse_sessions", "gauge",
               "Live SSE sessions by pump surface (logs / terminal / "
               "events / metrics).",
               [_fmt("ko_tpu_sse_sessions", {"surface": s}, n)
                for s, n in sorted(sse_sessions.items())])
        family("ko_tpu_sse_rows_delivered_total", "counter",
               "SSE frames written to consumer sockets since process "
               "start, by pump surface.",
               [_fmt("ko_tpu_sse_rows_delivered_total", {"surface": s}, n)
                for s, n in sorted(sse_rows.items())])
        family("ko_tpu_sse_lag_seconds", "gauge",
               "Wall-clock the most recent frame batch spent blocked in "
               "socket writes, by surface — the slow-consumer signal.",
               [_fmt("ko_tpu_sse_lag_seconds", {"surface": s},
                     round(v, 6))
                for s, v in sorted(sse_lag.items())])

        # ---- scrape-time collectors over the live stack ----
        clusters = services.repos.clusters.list()
        by_phase: dict[str, int] = {}
        for c in clusters:
            by_phase[c.status.phase] = by_phase.get(c.status.phase, 0) + 1
        family("ko_tpu_clusters", "gauge", "Clusters by lifecycle phase.",
               [_fmt("ko_tpu_clusters", {"phase": p}, n)
                for p, n in sorted(by_phase.items())])

        # operation-latency histograms off the span store (indexed SQL on
        # the mirrored columns, no JSON hydration): phase spans labeled by
        # phase name, task spans by playbook. Exemplar trace ids link a
        # slow bucket straight to `koctl trace`.
        histogram(
            "ko_tpu_phase_duration_seconds",
            "Adm phase wall-clock from persisted phase spans "
            "(docs/observability.md), by phase name.",
            "phase", services.repos.spans.duration_rows("phase"))
        histogram(
            "ko_tpu_task_duration_seconds",
            "Executor task wall-clock from persisted task spans, by "
            "playbook.",
            "playbook", services.repos.spans.duration_rows("task"))

        # journal + watchdog state (the robustness layer's own gauges)
        ops_by_status = services.repos.operations.count_by_status()
        family("ko_tpu_operations", "gauge",
               "Journal operations by status (Running = in flight right "
               "now; Interrupted = swept by the boot reconciler).",
               [_fmt("ko_tpu_operations", {"status": s}, n)
                for s, n in sorted(ops_by_status.items())])
        # fleet rollout waves by outcome (docs/resilience.md "Fleet
        # operations"), off the MIRRORED summary digests (migration 012)
        # — a 1000-rollout history must not hydrate every op's wave
        # ledger per scrape (ops predating the digest contribute nothing)
        from kubeoperator_tpu.fleet import FLEET_UPGRADE_KIND

        waves_by_outcome: dict[str, int] = {}
        fleet_in_flight = 0
        # getattr-guarded like the queue rows: exposition tests hand in
        # stub repos without the full OperationRepo surface
        summaries = getattr(services.repos.operations, "summaries",
                            lambda kind: [])
        for row in summaries(FLEET_UPGRADE_KIND):
            digest = row["summary"]
            for outcome, n in (digest.get("wave_outcomes") or {}).items():
                waves_by_outcome[str(outcome)] = \
                    waves_by_outcome.get(str(outcome), 0) + int(n)
            if row["status"] == "Running":
                fleet_in_flight += int(digest.get("in_flight", 0) or 0)
        family("ko_tpu_fleet_waves", "gauge",
               "Fleet rollout waves by outcome (promoted / canary-blocked "
               "/ rolled-back / failed / aborted / pending) across all "
               "journaled fleet operations.",
               [_fmt("ko_tpu_fleet_waves", {"outcome": o}, n)
                for o, n in sorted(waves_by_outcome.items())])
        family("ko_tpu_fleet_inflight_clusters", "gauge",
               "Clusters upgrading/gating right now across Running fleet "
               "rollouts (the concurrent wave engine's live lanes).",
               [_fmt("ko_tpu_fleet_inflight_clusters", {},
                     fleet_in_flight)])

        # convergence controller (docs/resilience.md "Fleet
        # convergence"): the last tick's verdict as a one-hot gauge plus
        # the drifted-cluster count, off the controller op's persisted
        # summary (no drift re-detection per scrape). getattr-guarded:
        # exposition tests hand in stubs without the converge service.
        converge = getattr(services, "converge", None)
        if converge is not None:
            last = (converge.status() or {}).get("last") or {}
            if not last:
                verdict = "idle"
            elif last.get("converged"):
                verdict = "converged"
            else:
                verdict = "drifting"
            family("ko_tpu_fleet_convergence", "gauge",
                   "Convergence controller verdict from its last tick "
                   "(one-hot: idle = never ticked, converged = zero "
                   "actionable drift, drifting = remediation pending).",
                   [_fmt("ko_tpu_fleet_convergence", {"verdict": v},
                         1 if v == verdict else 0)
                    for v in ("idle", "converged", "drifting")])
            family("ko_tpu_fleet_drifted_clusters", "gauge",
                   "Clusters the last convergence tick found drifted "
                   "(version skew, failed phase, or standing health "
                   "markers).",
                   [_fmt("ko_tpu_fleet_drifted_clusters", {},
                         int(last.get("drifted", 0) or 0))])

        # workload queue (docs/workloads.md "Queue and preemption"):
        # entries by state off the mirrored column, and the queue-wait
        # distribution by priority class (dispatch start - submission).
        # getattr-guarded like the watchdog rows for hand-built stubs.
        queue_repo = getattr(services.repos, "workload_queue", None)
        if queue_repo is not None:
            queue_counts = queue_repo.counts_by_state()
            family("ko_tpu_workload_queue", "gauge",
                   "Workload-queue entries by state (pending / placed / "
                   "running / drained / done / failed / cancelled).",
                   [_fmt("ko_tpu_workload_queue", {"state": s}, n)
                    for s, n in sorted(queue_counts.items())])
            # the concurrent engine's live lanes, split by class AND verb
            # (training vs serving) — the capacity question "who holds the
            # pool right now" the serial gauge above cannot answer.
            # getattr-guarded: pre-serve stubs omit the family.
            running_counts = getattr(queue_repo, "running_counts", None)
            if running_counts is not None:
                family("ko_tpu_workload_queue_running", "gauge",
                       "Running queue lanes by priority class and workload "
                       "kind (the concurrent dispatch engine's live gangs).",
                       [_fmt("ko_tpu_workload_queue_running",
                             {"priority": cls, "kind": kind}, n)
                        for (cls, kind), n
                        in sorted(running_counts().items())])
            histogram(
                "ko_tpu_workload_queue_wait_seconds",
                "Queue wait (first dispatch minus submission) per "
                "dispatched entry, by priority class.",
                "priority",
                [(cls, wait, "") for cls, wait
                 in queue_repo.wait_rows()])

        # live telemetry (docs/observability.md "Events and live
        # telemetry"): the event bus by kind, per-step training wall-
        # clock by tenant, and each op's latest loss — all off mirrored
        # columns. getattr-guarded like the queue rows for hand-built
        # exposition stubs; sample cardinality is bounded by op
        # retention (samples prune with their op's spans).
        events_repo = getattr(services.repos, "events", None)
        if events_repo is not None and hasattr(events_repo,
                                               "counts_by_kind"):
            family("ko_tpu_events_total", "counter",
                   "Durable event-bus rows by kind (retention-bounded: "
                   "rate() absorbs prune resets like process restarts).",
                   [_fmt("ko_tpu_events_total",
                         {"kind": k or "legacy"}, n)
                    for k, n in sorted(
                        events_repo.counts_by_kind().items())])
        samples_repo = getattr(services.repos, "metric_samples", None)
        if samples_repo is not None:
            histogram(
                "ko_tpu_workload_step_seconds",
                "Per-step training wall-clock from persisted metric "
                "samples, by tenant ('' = untenanted runs).",
                "tenant",
                [(tenant, step_s, "") for tenant, step_s
                 in samples_repo.step_rows()])
            # serving request latency (docs/workloads.md "Serving"): the
            # SLO surface per tenant, off the same mirrored sample
            # columns. hasattr-guarded: pre-serve sample stubs omit it.
            if hasattr(samples_repo, "request_rows"):
                histogram(
                    "ko_tpu_workload_request_seconds",
                    "Per-request serving latency from persisted metric "
                    "samples, by tenant — the SLO distribution "
                    "(docs/workloads.md \"Serving\").",
                    "tenant",
                    [(tenant, latency_s, "") for tenant, latency_s
                     in samples_repo.request_rows()])
            family("ko_tpu_workload_loss", "gauge",
                   "Latest per-op training loss from the metric-sample "
                   "ring (one series per retained workload op).",
                   [_fmt("ko_tpu_workload_loss",
                         {"op": op_id[:8], "tenant": tenant}, loss)
                    for op_id, tenant, _step, loss
                    in samples_repo.latest_losses()])

        # control-plane DB flight recorder (docs/observability.md
        # "Control-plane DB telemetry"): statement-level phase split off
        # the Database handle's in-memory accumulator. getattr-guarded
        # twice: exposition stubs carry no db, and a telemetry-off stack
        # carries db.telemetry=None — both simply omit the families.
        telemetry = getattr(getattr(services.repos, "db", None),
                            "telemetry", None)
        if telemetry is not None:
            from kubeoperator_tpu.observability.dbtelemetry import (
                DB_BUCKETS_S,
            )

            snap = telemetry.snapshot()
            lines = []
            for row in snap["statements"]:
                for phase in sorted(row["phases"]):
                    cell = row["phases"][phase]
                    labels = {"stmt": row["stmt"], "phase": phase}
                    cumulative = 0
                    for le, band in zip((*DB_BUCKETS_S, float("inf")),
                                        cell["buckets"]):
                        cumulative += band
                        le_text = ("+Inf" if le == float("inf")
                                   else f"{le:g}")
                        lines.append(_fmt(
                            "ko_tpu_db_statement_seconds_bucket",
                            {**labels, "le": le_text}, cumulative))
                    lines.append(_fmt("ko_tpu_db_statement_seconds_sum",
                                      labels, cell["sum_s"]))
                    lines.append(_fmt("ko_tpu_db_statement_seconds_count",
                                      labels, cell["count"]))
            family("ko_tpu_db_statement_seconds", "histogram",
                   "Control-plane statement wall-clock by stable "
                   "statement id and phase (lock_wait = blocked at "
                   "BEGIN IMMEDIATE, exec = statement execution, "
                   "commit = outermost COMMIT); ids match `koctl db "
                   "stats` and the KO-S statement model.", lines)
            family("ko_tpu_db_busy_retries_total", "counter",
                   "BEGIN IMMEDIATE attempts that hit another writer's "
                   "lock past busy_timeout (each is a bounded-backoff "
                   "retry; growth means WAL writer contention).",
                   [_fmt("ko_tpu_db_busy_retries_total", None,
                         snap["busy_retries"])])
            family("ko_tpu_db_lock_wait_seconds_total", "counter",
                   "Total wall-clock transactions spent blocked "
                   "acquiring the write lock (the scaling wall's "
                   "numerator — see PERF.md db rows).",
                   [_fmt("ko_tpu_db_lock_wait_seconds_total", None,
                         snap["lock_wait_s"])])
            family("ko_tpu_db_wal_bytes", "gauge",
                   "Size of the shared WAL file (growth between "
                   "checkpoints bounds reader catch-up work).",
                   [_fmt("ko_tpu_db_wal_bytes", None,
                         snap["wal_bytes"])])
            family("ko_tpu_db_tx_depth", "gauge",
                   "High-watermark of nested tx() scopes this process "
                   "has stacked (fence + journal write = 2).",
                   [_fmt("ko_tpu_db_tx_depth", None,
                         snap["tx_depth_max"])])

        try:
            watchdog_rows = services.watchdog.status()
        except Exception:
            watchdog_rows = None
        if watchdog_rows is not None:
            family("ko_tpu_watchdog_circuit_open", "gauge",
                   "1 when the cluster's auto-remediation circuit is open "
                   "(koctl watchdog reset closes it).",
                   [_fmt("ko_tpu_watchdog_circuit_open",
                         {"cluster": r["cluster"]},
                         1 if r["circuit"] == "open" else 0)
                    for r in watchdog_rows])
            family("ko_tpu_watchdog_budget_left", "gauge",
                   "Remediations left in the cluster's current window.",
                   [_fmt("ko_tpu_watchdog_budget_left",
                         {"cluster": r["cluster"]}, r["budget_left"])
                    for r in watchdog_rows])

        # controller leases (docs/resilience.md "Controller leases"): the
        # multi-controller ownership surface — who owns what from THIS
        # replica's viewpoint, and how stale its own heartbeats run.
        # getattr-guarded like the watchdog rows: hand-built test stubs
        # and pre-lease stacks simply omit the family.
        leases = getattr(services, "leases", None)
        if leases is not None and leases.enabled:
            counts = leases.state_counts()
            family("ko_tpu_controller_leases", "gauge",
                   "Controller leases by state from this replica's "
                   "viewpoint (held = ours and live; foreign = a live "
                   "peer's; expired = past deadline, sweepable by the "
                   "lease sweep).",
                   [_fmt("ko_tpu_controller_leases", {"state": s}, n)
                    for s, n in sorted(counts.items())])
            age = leases.max_heartbeat_age_s()
            family("ko_tpu_controller_lease_heartbeat_age_seconds", "gauge",
                   "Seconds since the oldest renewal among leases this "
                   "replica holds live (0 when it holds none); growth "
                   "toward lease.ttl_s means the heartbeat tick is "
                   "stalling.",
                   [_fmt("ko_tpu_controller_lease_heartbeat_age_seconds",
                         None, round(age, 3) if age is not None else 0)])

        try:
            stats = services.executor.task_stats()
        except Exception:
            # grpc backend with ko-runner down: scrape must not 500, and a
            # fabricated zero would read as "idle" — export up=0 and omit
            # the task families instead
            stats = None
        family("ko_tpu_executor_up", "gauge",
               "1 when the executor backend answers (for backend=grpc this "
               "is a liveness RPC against ko-runner).",
               [_fmt("ko_tpu_executor_up", None,
                     1 if stats is not None else 0)])
        if stats is not None:
            family("ko_tpu_executor_tasks_started_total", "counter",
                   "Playbook/adhoc tasks launched since process start.",
                   [_fmt("ko_tpu_executor_tasks_started_total", None,
                         stats["started_total"])])
            family("ko_tpu_executor_tasks", "gauge",
                   "Retained executor tasks by status (RUNNING = queue depth).",
                   [_fmt("ko_tpu_executor_tasks", {"status": s}, n)
                    for s, n in sorted(stats["by_status"].items())])

        term = services.terminals.stats()
        family("ko_tpu_terminal_sessions", "gauge",
               "Live web-terminal PTY sessions (reaped before counting).",
               [_fmt("ko_tpu_terminal_sessions", None, term["sessions"])])
        family("ko_tpu_terminal_dropped_chunks_total", "counter",
               "Output chunks dropped by the per-session scrollback cap "
               "(monotonic: closed sessions' drops are retained).",
               [_fmt("ko_tpu_terminal_dropped_chunks_total", None,
                     term["dropped_chunks_total"])])

        smoke_rows = []
        for c in clusters:
            if c.status.smoke_chips:
                smoke_rows.append(_fmt(
                    "ko_tpu_smoke_gbps",
                    {"cluster": c.name,
                     "simulated": str(bool(c.status.smoke_simulated)).lower()},
                    c.status.smoke_gbps,
                ))
        family("ko_tpu_smoke_gbps", "gauge",
               "Latest psum smoke bandwidth per TPU cluster (simulated "
               "label marks ko_simulation-fabricated values).", smoke_rows)

        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"
