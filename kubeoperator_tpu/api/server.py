"""ko-server REST API.

Route parity with the reference's iris router (`/api/v1/*`: clusters, hosts,
plans, regions, zones, projects, users, backup, events, components —
SURVEY.md §2.1 row 1a), plus TPU-first additions: `/plans/tpu-catalog`
(selectable slice shapes) and per-cluster smoke results in status.

Service calls that block (create with wait, phase runs) execute in a thread
pool so the event loop keeps streaming logs. Errors map KoError.http_status →
HTTP; bodies are i18n-translated using the session user's locale.
"""

from __future__ import annotations

import asyncio
import functools
import json
import time

from aiohttp import web

from kubeoperator_tpu.models import (
    BackupAccount,
    ClusterSpec,
    Credential,
    Plan,
    Region,
    Role,
    Zone,
)
from kubeoperator_tpu.service import Services
from kubeoperator_tpu.utils.errors import AuthError, KoError, NotFoundError
from kubeoperator_tpu.utils.i18n import translate
from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("api")

AUTH_EXEMPT = {("POST", "/api/v1/auth/login"), ("GET", "/api/v1/version"),
               ("GET", "/healthz"), ("GET", "/metrics")}


# ---------------------------------------------------------------- helpers ----
def require_fields(body, *names: str) -> tuple:
    """Pull required JSON body fields; a missing/empty field or a
    non-object body is a 400 with the field named — never a KeyError/
    AttributeError surfacing as ERR_INTERNAL (the whole class, fixed
    once, not per-endpoint)."""
    from kubeoperator_tpu.utils.errors import ValidationError

    if not isinstance(body, dict):
        raise ValidationError("request body must be a JSON object")
    values = []
    for name in names:
        value = body.get(name)
        if value is None or value == "":
            raise ValidationError(f"body needs {name!r}")
        values.append(value)
    return tuple(values)


def json_response(data, status: int = 200) -> web.Response:
    return web.json_response(data, status=status, dumps=functools.partial(
        json.dumps, default=str))


async def run_sync(request: web.Request, fn, *args, **kw):
    """Run a blocking service call off the event loop."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(
        None, functools.partial(fn, *args, **kw)
    )


# keystrokes and window resizes are high-volume and (input) sensitive —
# the audit trail records OPERATIONS, not terminal traffic. The skip is
# scoped to the terminal routes: a CLUSTER literally named "input" is a
# valid RFC1123 name and its deletion must still be audited.
_AUDIT_SKIP_PREFIX = "/api/v1/terminal/"
_AUDIT_SKIP_SUFFIXES = ("/input", "/resize")


def _audit_skipped(path: str) -> bool:
    return (path.startswith(_AUDIT_SKIP_PREFIX)
            and path.endswith(_AUDIT_SKIP_SUFFIXES))


async def _audit(request: web.Request, status: int) -> None:
    """Operation audit (reference parity: the operation-log screen): every
    mutating API call lands a who/what/when/status row. Bodies are never
    recorded — they can carry credentials. Best-effort: an audit failure
    must never fail the request it describes."""
    if request.method not in ("POST", "PUT", "DELETE"):
        return
    path = request.path
    if not path.startswith("/api/v1/") or _audit_skipped(path):
        return
    services = request.app.get(SERVICES_KEY)
    if services is None:
        return
    from kubeoperator_tpu.models import AuditRecord

    user = request.get("user")
    rec = AuditRecord(
        user_name=user.name if user is not None else "-",
        method=request.method, path=path, status=int(status),
        remote=request.remote or "",
    )
    try:
        await run_sync(request, services.repos.audit.record, rec)
    except Exception:  # pragma: no cover - diagnostics never sink requests
        log.exception("audit write failed")


@web.middleware
async def error_middleware(request: web.Request, handler):
    locale = request.headers.get("Accept-Language", "en-US").split(",")[0]
    if locale not in ("en-US", "zh-CN"):
        locale = "zh-CN" if locale.startswith("zh") else "en-US"
    metrics = request.app.get(METRICS_KEY)

    def observe(status: int):
        # /metrics scrapes would dominate their own counter; skip them
        if metrics is not None and request.path != "/metrics":
            metrics.observe_http(request.method, status)

    try:
        resp = await handler(request)
        observe(resp.status)
        await _audit(request, resp.status)
        return resp
    except KoError as e:
        observe(e.http_status)
        await _audit(request, e.http_status)
        return json_response(
            {"error": e.code,
             "message": translate(e.code, locale, message=e.message,
                                  **e.args_map)},
            status=e.http_status,
        )
    except web.HTTPException as e:
        observe(e.status)
        await _audit(request, e.status)
        raise
    except (ConnectionResetError, BrokenPipeError):
        # routine SSE/terminal client disconnect mid-stream — 499 (client
        # closed request), NOT a 500: a steady error rate proportional to
        # SSE usage would mask real failures on the dashboard
        observe(499)
        raise
    except Exception as e:  # pragma: no cover - last resort
        log.exception("unhandled API error")
        observe(500)
        await _audit(request, 500)
        return json_response(
            {"error": "ERR_INTERNAL", "message": str(e)}, status=500
        )


# typed app-state key (aiohttp AppKey): silences NotAppKeyWarning and
# gives every request.app[SERVICES_KEY] read a real type
SERVICES_KEY: "web.AppKey[Services]" = web.AppKey("services", object)
METRICS_KEY = web.AppKey("metrics", object)


@web.middleware
async def auth_middleware(request: web.Request, handler):
    if (request.method, request.path) in AUTH_EXEMPT or \
            not request.path.startswith("/api/"):
        return await handler(request)
    services: Services = request.app[SERVICES_KEY]
    token = request.headers.get("Authorization", "").removeprefix("Bearer ").strip()
    if not token:
        token = request.cookies.get("ko_session", "")
    if not token:
        raise AuthError()
    request["user"] = await run_sync(request, services.users.authenticate, token)
    return await handler(request)


def _require_admin(request: web.Request) -> None:
    user = request.get("user")
    if user is not None and not user.is_admin:
        from kubeoperator_tpu.utils.errors import ForbiddenError

        raise ForbiddenError(action="admin operation")


def admin_guard(handler):
    """Admin-only route (infra CRUD writes: credentials/regions/zones/plans/
    hosts/backup-accounts — reference: platform-level resources)."""
    @functools.wraps(handler)
    async def wrapped(request: web.Request):
        _require_admin(request)
        return await handler(request)
    return wrapped


def cluster_guard(handler, needed: Role):
    """Project RBAC on /clusters/{name}/* routes (reference `pkg/permission`):
    admin passes; project-scoped clusters check the member role; unscoped
    clusters are viewable by any authenticated user but writable only by
    admins."""
    @functools.wraps(handler)
    async def wrapped(request: web.Request):
        from kubeoperator_tpu.utils.errors import ForbiddenError

        services: Services = request.app[SERVICES_KEY]
        user = request["user"]
        if not user.is_admin:
            cluster = await run_sync(request, services.clusters.get,
                                     request.match_info["name"])
            if cluster.project_id:
                await run_sync(request, services.projects.require,
                               user, cluster.project_id, needed)
            elif needed is not Role.VIEWER:
                raise ForbiddenError(action=f"{needed.value} on cluster")
        return await handler(request)
    return wrapped


# ---------------------------------------------------------------- handlers ---
class Handlers:
    def __init__(self, services: Services):
        self.s = services
        from kubeoperator_tpu.api.metrics import MetricsRegistry

        self.metrics = MetricsRegistry()
        self._analysis_cache: tuple | None = None  # (plain dict, sarif dict)

    async def bundle_manifest_view(self, request):
        """Version-management screen data (reference parity: the console's
        version/manifest page): platform version, supported K8s hops,
        pinned component versions, and the offline artifact counts — what
        an air-gapped operator can actually install."""
        _require_admin(request)
        from kubeoperator_tpu.registry import bundle_manifest
        from kubeoperator_tpu.registry.manifest import COMPONENT_VERSIONS

        manifest = await run_sync(request, bundle_manifest)
        by_kind: dict = {}
        for artifact in manifest.get("artifacts", []):
            kind = str(artifact).split("/", 1)[0]
            by_kind[kind] = by_kind.get(kind, 0) + 1
        return json_response({
            "version": manifest.get("version", ""),
            "k8s_versions": manifest.get("k8s_versions", []),
            "component_versions": dict(COMPONENT_VERSIONS),
            "artifact_counts": by_kind,
            "artifact_total": len(manifest.get("artifacts", [])),
        })

    async def analysis_report(self, request):
        """ko-analyze over the running platform's own installed tree — the
        console's static-health view (same JSON as `koctl lint --format
        json`; `?format=sarif` returns SARIF 2.1.0 for CI annotators).
        Admin-gated: findings name internal file paths. Cached per process
        after the first call (the installed tree cannot change under a
        running server), `?fresh=1` forces a re-run."""
        _require_admin(request)
        from kubeoperator_tpu.analysis import run_analysis, to_sarif

        if request.query.get("fresh") == "1":
            self._analysis_cache = None
        if self._analysis_cache is None:
            report = await run_sync(request, run_analysis)
            self._analysis_cache = (report.to_dict(), to_sarif(report))
        plain, sarif = self._analysis_cache
        if request.query.get("format") == "sarif":
            return json_response(sarif)
        return json_response(plain)

    async def db_stats(self, request):
        """The control-plane flight recorder's top-N statement table
        (docs/observability.md "Control-plane DB telemetry"): per-
        statement lock-wait/exec/commit totals and p99s, the lock-wait
        share headline, busy retries and WAL size — `koctl db stats`
        over both transports. Admin-gated like /analysis: statement
        texts name internal schema. Telemetry off answers
        `{"enabled": false}` instead of 404ing, so dashboards can tell
        "knob off" from "endpoint missing"."""
        from kubeoperator_tpu.utils.errors import ValidationError

        _require_admin(request)
        telemetry = getattr(self.s.repos.db, "telemetry", None)
        if telemetry is None:
            return json_response({"enabled": False, "statements": []})
        try:
            top = int(request.query.get("top", "10") or 10)
        except ValueError:
            raise ValidationError("top must be an integer")
        return json_response(await run_sync(
            request, telemetry.stats, max(1, min(top, 100))))

    async def audit_log(self, request):
        from kubeoperator_tpu.utils.errors import ValidationError

        _require_admin(request)
        try:
            limit = int(request.query.get("limit", "200") or 200)
        except ValueError:
            # same contract as the events feed: bad input is a 400 with
            # the field named, not an ERR_INTERNAL 500
            raise ValidationError("limit must be an integer")
        limit = max(1, min(limit, 1000))
        rows = await run_sync(request, self.s.repos.audit.tail, limit)
        return json_response([r.to_dict() for r in rows])

    async def metrics_endpoint(self, request):
        # /metrics is session-auth-exempt (scrapers have no session), which
        # leaves cluster names/phases readable by anyone reaching the port.
        # server.metrics_token gates it without relying on network
        # placement alone (ADVICE r4): prometheus sends it via the scrape
        # config's `authorization: credentials:` field. Empty = open,
        # matching the compose's internal-network default.
        token = self.s.config.get("server.metrics_token", "")
        if token:
            import hmac

            got = request.headers.get("Authorization", "")
            # constant-time compare: the knob exists precisely for ports
            # reachable by untrusted networks — no timing oracle
            if not hmac.compare_digest(got, f"Bearer {token}"):
                return web.Response(status=401, text="metrics token required")
        # OpenMetrics negotiation: exemplar-bearing exposition (trace ids
        # on histogram buckets) only for scrapers that ask for it — the
        # classic 0.0.4 text parser rejects exemplars
        openmetrics = "application/openmetrics-text" in \
            request.headers.get("Accept", "")
        text = await run_sync(request, self.metrics.render, self.s,
                              openmetrics)
        if openmetrics:
            return web.Response(
                text=text, charset="utf-8",
                content_type="application/openmetrics-text",
            )
        return web.Response(
            text=text, content_type="text/plain", charset="utf-8"
        )

    # ---- auth / users ----
    async def login(self, request):
        body = await request.json()
        token = await run_sync(request, self.s.users.login,
                               body.get("username", ""), body.get("password", ""))
        resp = json_response({"token": token})
        resp.set_cookie("ko_session", token, httponly=True, samesite="Lax")
        return resp

    async def logout(self, request):
        token = request.headers.get("Authorization", "").removeprefix("Bearer ")
        token = token.strip() or request.cookies.get("ko_session", "")
        await run_sync(request, self.s.users.logout, token)
        resp = json_response({"ok": True})
        resp.del_cookie("ko_session")
        return resp

    async def whoami(self, request):
        return json_response(request["user"].to_public_dict())

    async def change_password(self, request):
        """Self-service: re-proves the OLD password (a stolen session token
        must not be enough to lock the real owner out), then invalidates
        nothing but the credential — existing sessions stay valid."""
        body = await request.json()
        await run_sync(request, self.s.users.change_password,
                       request["user"].name,
                       body.get("old", ""), body.get("new", ""))
        return json_response({"ok": True})

    async def list_users(self, request):
        _require_admin(request)
        users = await run_sync(request, self.s.users.list)
        return json_response([u.to_public_dict() for u in users])

    async def create_user(self, request):
        _require_admin(request)
        body = await request.json()
        user = await run_sync(
            request, self.s.users.create, body["name"],
            body.get("password", ""), body.get("email", ""),
            body.get("is_admin", False), body.get("source", "local"),
        )
        return json_response(user.to_public_dict(), status=201)

    # ---- ldap (admin) ----
    async def get_ldap_settings(self, request):
        return json_response(
            await run_sync(request, self.s.ldap.settings.get_public))

    async def update_ldap_settings(self, request):
        body = await request.json()
        return json_response(
            await run_sync(request, self.s.ldap.settings.update, body))

    async def ldap_test(self, request):
        _require_admin(request)
        return json_response(await run_sync(request, self.s.ldap.test_connection))

    async def ldap_sync(self, request):
        _require_admin(request)
        return json_response(await run_sync(request, self.s.ldap.sync_users))

    # ---- version / health ----
    async def version(self, request):
        from kubeoperator_tpu.version import SUPPORTED_K8S_VERSIONS, __version__

        return json_response({
            "version": __version__,
            "supported_k8s_versions": list(SUPPORTED_K8S_VERSIONS),
        })

    async def healthz(self, request):
        """Liveness WITH substance: `koctl status` and the compose
        healthcheck learn whether the state store answers and which
        executor backend is live, not just that aiohttp accepts TCP. A
        dead DB turns the status to 503 — a server that cannot read state
        is not healthy, whatever its socket says."""
        from kubeoperator_tpu.version import __version__

        def probe():
            try:
                self.s.repos.db.query("SELECT 1")
                return True
            except Exception:
                # the 503 alone says "degraded"; the WHY belongs in the log
                log.exception("healthz: state store probe failed")
                return False

        def probe_executor():
            # in-process backends answer from their own registry; the grpc
            # backend turns this into a liveness RPC against ko-runner — a
            # server whose runner is down cannot mutate clusters, so it
            # degrades just like a dead DB does
            try:
                self.s.executor.task_stats()
                return True
            except Exception:
                log.exception("healthz: executor probe failed")
                return False

        # concurrent probes: a hung runner (5s Stats deadline) must not
        # stack on top of the DB probe's latency
        db_ok, exec_ok = await asyncio.gather(
            run_sync(request, probe), run_sync(request, probe_executor)
        )
        healthy = db_ok and exec_ok
        body = {
            "status": "ok" if healthy else "degraded",
            "version": __version__,
            "db": db_ok,
            "executor": type(self.s.executor).__name__,
            "executor_ok": exec_ok,
        }
        return json_response(body, status=200 if healthy else 503)

    # ---- clusters (§3.1) ----
    async def list_clusters(self, request):
        project = request.query.get("project") or None
        user = request["user"]

        def gather():
            # one membership query off-loop via _visible_clusters, then a
            # set filter — never N per-cluster lookups on the event loop
            clusters = self._visible_clusters(user)
            if project:
                clusters = [c for c in clusters if c.project_id == project]
            return [c.to_public_dict() for c in clusters]

        return json_response(await run_sync(request, gather))

    async def create_cluster(self, request):
        body = await request.json()
        user = request["user"]
        if not user.is_admin:
            project_id = body.get("project_id", "")
            if not project_id:
                from kubeoperator_tpu.utils.errors import ForbiddenError

                raise ForbiddenError(
                    action="creating a cluster outside a project"
                )
            await run_sync(request, request.app[SERVICES_KEY].projects.require,
                           user, project_id, Role.MANAGER)
        spec = ClusterSpec(**{
            k: v for k, v in body.get("spec", {}).items()
            if k in ClusterSpec.__dataclass_fields__
        })
        cluster = await run_sync(
            request, self.s.clusters.create,
            body["name"],
            spec=spec,
            provision_mode=body.get("provision_mode", "manual"),
            plan_name=body.get("plan", ""),
            project_id=body.get("project_id", ""),
            host_names=body.get("hosts", []),
            credential_name=body.get("credential", ""),
            wait=False,
        )
        return json_response(cluster.to_public_dict(), status=201)

    async def get_cluster(self, request):
        cluster = await run_sync(request, self.s.clusters.get,
                                 request.match_info["name"])
        return json_response(cluster.to_public_dict())

    async def cluster_status(self, request):
        data = await run_sync(request, self.s.clusters.status_payload,
                              request.match_info["name"])
        return json_response(data)

    async def delete_cluster(self, request):
        await run_sync(request, self.s.clusters.delete,
                       request.match_info["name"], False)
        return json_response({"ok": True}, status=202)

    async def import_cluster(self, request):
        _require_admin(request)
        body = await request.json()
        cluster = await run_sync(
            request, self.s.clusters.import_cluster,
            str(body.get("name", "")).strip(), body.get("kubeconfig", ""),
            body.get("project_id", ""))
        return json_response(cluster.to_public_dict(), status=201)

    async def retry_cluster(self, request):
        cluster = await run_sync(request, self.s.clusters.retry,
                                 request.match_info["name"], False)
        return json_response(cluster.to_public_dict(), status=202)

    async def scale_slices(self, request):
        body = await request.json()
        raw = body.get("num_slices")
        if isinstance(raw, bool) or not isinstance(raw, int) or raw < 1:
            from kubeoperator_tpu.utils.errors import ValidationError

            raise ValidationError("num_slices must be a positive integer")
        cluster = await run_sync(request, self.s.clusters.scale_slices,
                                 request.match_info["name"], raw, False)
        return json_response(cluster.to_public_dict(), status=202)

    async def replace_slice(self, request):
        body = await request.json()
        raw = body.get("slice_id")
        if isinstance(raw, bool) or not isinstance(raw, int) or raw < 0:
            from kubeoperator_tpu.utils.errors import ValidationError

            raise ValidationError("slice_id must be a non-negative integer")
        cluster = await run_sync(request, self.s.clusters.replace_slice,
                                 request.match_info["name"], raw, False)
        return json_response(cluster.to_public_dict(), status=202)

    async def cluster_slices(self, request):
        data = await run_sync(request, self.s.clusters.slice_status,
                              request.match_info["name"])
        return json_response(data)

    async def rotate_encryption(self, request):
        cluster = await run_sync(
            request, self.s.clusters.rotate_encryption_key,
            request.match_info["name"], False)
        return json_response(cluster.to_public_dict(), status=202)

    async def renew_certs(self, request):
        cluster = await run_sync(request, self.s.clusters.renew_certs,
                                 request.match_info["name"], False)
        return json_response(cluster.to_public_dict(), status=202)

    async def etcd_maintenance(self, request):
        cluster = await run_sync(request, self.s.clusters.etcd_maintenance,
                                 request.match_info["name"], False)
        return json_response(cluster.to_public_dict(), status=202)

    async def cluster_kubeconfig(self, request):
        cluster = await run_sync(request, self.s.clusters.get,
                                 request.match_info["name"])
        if not cluster.kubeconfig:
            raise NotFoundError(kind="kubeconfig", name=cluster.name)
        return web.Response(text=cluster.kubeconfig,
                            content_type="application/yaml")

    async def cluster_logs(self, request):
        """Task-log streaming: SSE when `follow=1`, else JSON page.

        The SSE stream is the reference's websocket log viewer analog
        (SURVEY.md §5.1)."""
        name = request.match_info["name"]
        cluster = await run_sync(request, self.s.clusters.get, name)
        task_id = request.query.get("task", "")
        cursor = int(request.query.get("after", "-1" if task_id else "0"))

        def fetch(after: int):
            """(chunks, new_cursor): per-task seq cursor, or the cluster-wide
            rowid cursor — both O(new rows) in SQL."""
            if task_id:
                chunks = self.s.repos.task_logs.tail(task_id, after)
                return chunks, (chunks[-1].seq if chunks else after)
            return self.s.repos.task_logs.tail_cluster(cluster.id, after)

        if request.query.get("follow") != "1":
            chunks, _ = await run_sync(request, fetch, cursor)
            return json_response([
                {"seq": c.seq, "task_id": c.task_id, "line": c.line}
                for c in chunks
            ])

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        self.metrics.sse_started("logs")
        try:
            idle = 0.0
            while idle < 30.0:
                chunks, cursor = await run_sync(request, fetch, cursor)
                if chunks:
                    idle = 0.0
                    write_t0 = time.monotonic()
                    for c in chunks:
                        await resp.write(
                            f"data: {json.dumps({'seq': c.seq, 'line': c.line})}\n\n"
                            .encode()
                        )
                    self.metrics.sse_rows_delivered("logs", len(chunks))
                    self.metrics.sse_write_lag(
                        "logs", time.monotonic() - write_t0)
                else:
                    idle += 0.5
                    await asyncio.sleep(0.5)
            await resp.write(b"event: end\ndata: {}\n\n")
        finally:
            self.metrics.sse_finished("logs")
        return resp

    # ---- nodes / scale (§3.3) ----
    async def list_nodes(self, request):
        nodes = await run_sync(request, self.s.nodes.list,
                               request.match_info["name"])
        return json_response([n.to_public_dict() for n in nodes])

    async def scale_up(self, request):
        body = await request.json()
        nodes = await run_sync(request, self.s.nodes.scale_up,
                               request.match_info["name"], body.get("hosts", []))
        return json_response([n.to_public_dict() for n in nodes], status=201)

    async def scale_down(self, request):
        await run_sync(request, self.s.nodes.scale_down,
                       request.match_info["name"],
                       request.match_info["node"])
        return json_response({"ok": True})

    # ---- upgrade (§3.4) ----
    async def upgrade(self, request):
        body = await request.json()
        (version,) = require_fields(body, "version")
        cluster = await run_sync(request, self.s.upgrades.upgrade,
                                 request.match_info["name"], version)
        return json_response(cluster.to_public_dict())

    # ---- backup (§3.5) ----
    async def list_backup_accounts(self, request):
        accounts = await run_sync(request, self.s.backups.list_accounts)
        return json_response([a.to_public_dict() for a in accounts])

    async def create_backup_account(self, request):
        body = await request.json()
        account = await run_sync(
            request, self.s.backups.create_account,
            BackupAccount(name=body["name"], type=body.get("type", "local"),
                          bucket=body.get("bucket", ""),
                          vars=body.get("vars", {})),
        )
        return json_response(account.to_public_dict(), status=201)

    async def test_backup_account(self, request):
        # reachability probe (socket-level), like LDAP's test button: a bad
        # endpoint surfaces here, not in the 3am cron backup
        result = await run_sync(
            request, self.s.backups.test_account, request.match_info["name"]
        )
        return json_response(result)

    async def run_backup(self, request):
        body = await request.json() if request.can_read_body else {}
        record = await run_sync(request, self.s.backups.run_backup,
                                request.match_info["name"],
                                body.get("account", ""))
        return json_response(record.to_public_dict(), status=201)

    async def list_backups(self, request):
        files = await run_sync(request, self.s.backups.list_files,
                               request.match_info["name"])
        return json_response([f.to_public_dict() for f in files])

    async def restore(self, request):
        body = await request.json()
        (file_name,) = require_fields(body, "file")
        await run_sync(request, self.s.backups.restore,
                       request.match_info["name"], file_name)
        return json_response({"ok": True})

    async def app_backup(self, request):
        body = await request.json() if request.can_read_body else {}
        name = await run_sync(request, self.s.backups.app_backup,
                              request.match_info["name"],
                              body.get("backup_name", ""),
                              body.get("namespaces", ""))
        return json_response({"backup": name}, status=201)

    async def app_restore(self, request):
        body = await request.json()
        (backup,) = require_fields(body, "backup")
        await run_sync(request, self.s.backups.app_restore,
                       request.match_info["name"], backup)
        return json_response({"ok": True})

    async def backup_strategy(self, request):
        if request.method == "GET":
            strategy = await run_sync(request, self.s.backups.get_strategy,
                                      request.match_info["name"])
            return json_response(
                strategy.to_public_dict() if strategy else None
            )
        body = await request.json()
        (account,) = require_fields(body, "account")
        strategy = await run_sync(
            request, self.s.backups.set_strategy,
            request.match_info["name"], account,
            body.get("cron", "0 3 * * *"), body.get("save_num", 7),
            body.get("enabled", True),
        )
        return json_response(strategy.to_public_dict())

    # ---- health ----
    async def health(self, request):
        report = await run_sync(request, self.s.health.check,
                                request.match_info["name"])
        return json_response(report.to_dict())

    async def cluster_operations(self, request):
        """Operation-journal history (newest first, incl. interrupted ops
        swept by the boot reconciler) — `koctl cluster operations`."""
        def gather():
            cluster = self.s.clusters.get(request.match_info["name"])
            limit = int(request.query.get("limit", 50))
            return [op.to_dict()
                    for op in self.s.journal.history(cluster.id, limit)]

        return json_response(await run_sync(request, gather))

    async def watchdog_status(self, request):
        return json_response(await run_sync(request, self.s.watchdog.status))

    async def watchdog_reset(self, request):
        return json_response(await run_sync(
            request, self.s.watchdog.reset, request.match_info["name"]))

    # ---- fleet rollouts (docs/resilience.md "Fleet operations") ----
    async def fleet_upgrade(self, request):
        from kubeoperator_tpu.fleet import upgrade_kwargs

        body = await request.json()
        (target,) = require_fields(body, "target")
        result = await run_sync(
            request, self.s.fleet.upgrade, target,
            wait=False, **upgrade_kwargs(body))
        return json_response(result, status=202)

    async def fleet_operations(self, request):
        return json_response(await run_sync(request, self.s.fleet.list_ops))

    async def fleet_drift(self, request):
        from kubeoperator_tpu.fleet.planner import drift_kwargs

        return json_response(await run_sync(
            request, self.s.fleet.drift,
            **drift_kwargs(dict(request.query))))

    async def fleet_converge_status(self, request):
        return json_response(await run_sync(
            request, self.s.converge.status))

    async def fleet_converge_run(self, request):
        from kubeoperator_tpu.fleet import converge_kwargs

        body = await request.json() if request.can_read_body else {}
        return json_response(await run_sync(
            request, self.s.converge.run_once, **converge_kwargs(body)))

    async def fleet_operation(self, request):
        return json_response(await run_sync(
            request, self.s.fleet.status, request.match_info["op"]))

    async def fleet_pause(self, request):
        return json_response(await run_sync(
            request, self.s.fleet.pause, request.match_info["op"]))

    async def fleet_resume(self, request):
        return json_response(await run_sync(
            request, self.s.fleet.resume, request.match_info["op"]))

    async def fleet_abort(self, request):
        return json_response(await run_sync(
            request, self.s.fleet.abort, request.match_info["op"]))

    async def fleet_trace(self, request):
        return json_response(await run_sync(
            request, self.s.fleet.trace, request.match_info["op"]))

    # ---- tenant workloads (docs/workloads.md) ----
    async def workload_train(self, request):
        from kubeoperator_tpu.service.workload import train_kwargs

        body = await request.json() if request.can_read_body else {}
        result = await run_sync(
            request, self.s.workloads.train, **train_kwargs(body))
        return json_response(result, status=201)

    async def workload_operations(self, request):
        return json_response(
            await run_sync(request, self.s.workloads.list_ops))

    async def workload_checkpoints(self, request):
        tenant = str(request.query.get("tenant", "") or "")
        return json_response(
            await run_sync(request, self.s.workloads.checkpoints, tenant))

    # ---- workload queue (docs/workloads.md "Queue and preemption") ----
    async def workload_submit(self, request):
        from kubeoperator_tpu.service.queue import submit_kwargs

        body = await request.json() if request.can_read_body else {}
        result = await run_sync(
            request, self.s.workload_queue.submit, **submit_kwargs(body))
        return json_response(result, status=201)

    async def workload_queue(self, request):
        return json_response(
            await run_sync(request, self.s.workload_queue.queue_view))

    async def workload_queue_entry(self, request):
        return json_response(await run_sync(
            request, self.s.workload_queue.status,
            request.match_info["entry"]))

    async def workload_queue_cancel(self, request):
        return json_response(await run_sync(
            request, self.s.workload_queue.cancel,
            request.match_info["entry"]))

    async def workload_operation(self, request):
        return json_response(await run_sync(
            request, self.s.workloads.status, request.match_info["op"]))

    async def workload_trace(self, request):
        return json_response(await run_sync(
            request, self.s.workloads.trace, request.match_info["op"]))

    async def recover(self, request):
        body = await request.json()
        await run_sync(request, self.s.health.recover,
                       request.match_info["name"], body["probe"])
        return json_response({"ok": True})

    # ---- components ----
    async def component_catalog(self, request):
        return json_response(self.s.components.catalog())

    async def get_notify_settings(self, request):
        return json_response(
            await run_sync(request, self.s.notify_settings.get_public))

    async def update_notify_settings(self, request):
        body = await request.json()
        return json_response(
            await run_sync(request, self.s.notify_settings.update, body))

    async def test_notify_channel(self, request):
        body = await request.json()
        return json_response(await run_sync(
            request, self.s.notify_settings.test,
            body.get("channel", ""), request["user"].id))

    async def providers_catalog(self, request):
        """The declared provider-vars contract (provisioner/providers.py):
        the console renders region/zone forms from this instead of a raw
        JSON textarea, so typos and missing credentials die client-side."""
        from kubeoperator_tpu.provisioner.providers import PROVIDER_VARS

        return json_response(PROVIDER_VARS)

    async def list_components(self, request):
        comps = await run_sync(request, self.s.components.list,
                               request.match_info["name"])
        return json_response([c.to_public_dict() for c in comps])

    async def install_component(self, request):
        body = await request.json()
        (component,) = require_fields(body, "component")
        comp = await run_sync(request, self.s.components.install,
                              request.match_info["name"], component,
                              body.get("vars"))
        return json_response(comp.to_public_dict(), status=201)

    async def uninstall_component(self, request):
        await run_sync(request, self.s.components.uninstall,
                       request.match_info["name"],
                       request.match_info["component"])
        return json_response({"ok": True})

    # ---- cis scans ----
    async def run_cis_scan(self, request):
        scan = await run_sync(request, self.s.cis.run_scan,
                              request.match_info["name"])
        return json_response(scan.to_public_dict(), status=201)

    async def list_cis_scans(self, request):
        scans = await run_sync(request, self.s.cis.list,
                               request.match_info["name"])
        return json_response([s.to_public_dict() for s in scans])

    async def get_cis_scan(self, request):
        scan = await run_sync(request, self.s.cis.get,
                              request.match_info["name"],
                              request.match_info["scan"])
        return json_response(scan.to_public_dict())

    async def delete_cis_scan(self, request):
        await run_sync(request, self.s.cis.delete,
                       request.match_info["name"], request.match_info["scan"])
        return json_response({"ok": True})

    # ---- web terminal (webkubectl analog) ----
    def _owned_terminal(self, request):
        """Attach guard: only the opener (or an admin) may touch a session."""
        session = self.s.terminals.get(request.match_info["session"])
        user = request["user"]
        if not user.is_admin and session.user_id != user.id:
            from kubeoperator_tpu.utils.errors import ForbiddenError

            raise ForbiddenError(action="attach to another user's terminal")
        return session

    async def open_terminal(self, request):
        # The shell runs as the server process (containerized in the platform
        # bundle, but still the control-plane trust domain), so opening is
        # admin-only unless the operator explicitly extends it to project
        # managers via terminal.allow_project_managers.
        if not request["user"].is_admin and not self.s.config.get(
            "terminal.allow_project_managers", False
        ):
            from kubeoperator_tpu.utils.errors import ForbiddenError

            raise ForbiddenError(action="opening a terminal (admin-only)")
        session = await run_sync(request, self.s.terminals.open,
                                 request.match_info["name"],
                                 request["user"].id)
        return json_response(
            {"id": session.id, "cluster": session.cluster_name}, status=201
        )

    async def list_terminals(self, request):
        _require_admin(request)
        return json_response(await run_sync(request, self.s.terminals.list))

    async def terminal_input(self, request):
        session = self._owned_terminal(request)
        body = await request.json()
        data = body.get("data", "")
        await run_sync(request, session.write, data.encode())
        return json_response({"ok": True})

    async def terminal_output(self, request):
        session = self._owned_terminal(request)
        after = int(request.query.get("after", "-1"))
        if request.query.get("follow") != "1":
            missed, chunks = await run_sync(request, session.read_with_gap,
                                            after)
            return json_response({
                "alive": session.alive,
                # chunks the scrollback cap dropped before this poll could
                # read them — the client renders a gap marker, never a
                # silent splice
                "missed": missed,
                "chunks": [
                    {"seq": s, "data": d.decode("utf-8", "replace")}
                    for s, d in chunks
                ],
            })
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        async def flush(after_seq: int) -> int:
            missed, chunks = await run_sync(request, session.read_with_gap,
                                            after_seq)
            if missed and chunks:
                # the gap precedes the chunks about to stream
                await resp.write(
                    f"event: gap\ndata: {json.dumps({'missed': missed})}\n\n"
                    .encode())
            write_t0 = time.monotonic()
            for s, d in chunks:
                payload = json.dumps(
                    {"seq": s, "data": d.decode("utf-8", "replace")}
                )
                await resp.write(f"data: {payload}\n\n".encode())
            if chunks:
                self.metrics.sse_rows_delivered("terminal", len(chunks))
                self.metrics.sse_write_lag(
                    "terminal", time.monotonic() - write_t0)
            return chunks[-1][0] if chunks else after_seq

        self.metrics.sse_started("terminal")
        try:
            idle = 0.0
            while idle < 60.0 and session.alive:
                new_after = await flush(after)
                if new_after != after:
                    idle = 0.0
                    after = new_after
                else:
                    idle += 0.2
                    await asyncio.sleep(0.2)
            # final drain: the shell's last output lands in the buffer just
            # before `alive` flips, after the loop's last read
            await flush(after)
            # the client must know WHY the stream ended: idle-timeout
            # (reconnect, carrying the cursor) vs dead session (stop —
            # otherwise an exited shell becomes a tight reconnect loop
            # until the reaper catches up)
            await resp.write(
                f"event: end\ndata: "
                f"{json.dumps({'alive': session.alive})}\n\n".encode())
        finally:
            self.metrics.sse_finished("terminal")
        return resp

    async def terminal_resize(self, request):
        session = self._owned_terminal(request)
        body = await request.json()
        session.resize(int(body.get("rows", 24)), int(body.get("cols", 80)))
        return json_response({"ok": True})

    async def close_terminal(self, request):
        self._owned_terminal(request)
        await run_sync(request, self.s.terminals.close,
                       request.match_info["session"])
        return json_response({"ok": True})

    # ---- events ----
    async def cluster_events(self, request):
        cluster = await run_sync(request, self.s.clusters.get,
                                 request.match_info["name"])
        events = await run_sync(request, self.s.events.list, cluster.id)
        return json_response([e.to_public_dict() for e in events])

    def _visible_clusters(self, user):
        """The LIST visibility rule (admin: all; member: own projects) —
        shared by the cluster list and the activity feed so the activity
        tab always summarizes exactly the clusters rendered beside it.
        Deliberately narrower than cluster_guard's per-cluster VIEW rule
        (which also lets any authenticated user read an unscoped cluster
        by name): the fleet views show what you belong to; direct reads
        reach what you may inspect. Sync; callers wrap in run_sync."""
        clusters = self.s.clusters.list(None)
        if user.is_admin:
            return clusters
        member_of = {
            m.project_id
            for m in self.s.repos.project_members.find(user_id=user.id)
        }
        return [c for c in clusters if c.project_id in member_of]

    def _event_stream_params(self, request) -> dict:
        """Parse + authorize the event-STREAM form of /api/v1/events
        (follow / kind / tenant / cluster / after). Platform-wide
        streaming is admin-only (bus events cross project boundaries); a
        non-admin may follow ONE cluster they can see. Returns the
        `EventRepo.since` kwargs plus the starting cursor."""
        from kubeoperator_tpu.utils.errors import (
            ForbiddenError,
            ValidationError,
        )

        query = request.query
        cluster_id: str | None = None
        if query.get("cluster"):
            cluster = self.s.clusters.get(query["cluster"])
            cluster_id = cluster.id
        user = request.get("user")
        if user is not None and not user.is_admin:
            if cluster_id is None:
                raise ForbiddenError(
                    action="streaming platform-wide events (pass "
                           "?cluster= or ask an admin)")
            member_of = {c.id for c in self._visible_clusters(user)}
            if cluster_id not in member_of:
                raise ForbiddenError(action="streaming another "
                                            "project's events")
        # `Last-Event-ID` (the SSE reconnect contract) wins over the
        # `after` query param — a dropped console resumes exactly where
        # its last received frame's id left off
        raw = request.headers.get("Last-Event-ID",
                                  query.get("after", "0")) or "0"
        try:
            after = int(raw)
        except ValueError:
            raise ValidationError("event cursor must be an integer rowid")
        return {
            "after": max(after, 0),
            "kind": str(query.get("kind", "") or ""),
            "tenant": str(query.get("tenant", "") or ""),
            "cluster_id": cluster_id,
        }

    @staticmethod
    def _event_row(rowid: int, event) -> dict:
        row = event.to_public_dict()
        row["stream_id"] = rowid
        return row

    # SSE posture shared by every follow stream: poll cadence, the idle
    # window after which the stream honestly ends, and the keep-alive
    # comment cadence that proves liveness through buffering proxies
    _SSE_POLL_S = 0.25
    _SSE_IDLE_END_S = 30.0
    _SSE_KEEPALIVE_S = 5.0

    async def _sse_follow(self, request, fetch, *, event_name=None,
                          end_payload=None, live=None, surface="events"):
        """Generic SSE pump: `fetch()` (run off-loop) returns a list of
        (rowid, json-serializable row [, name]) frames; each frame is
        written as `id:`/`event:`/`data:` lines, idle gaps emit
        keep-alive comments, and the stream closes with `event: end`
        after the idle window (or the moment `live()` turns false —
        e.g. a watched op reaching a terminal state). `surface` labels
        the session/rows/lag accounting in /metrics (the SSE fanout
        denominator, docs/observability.md)."""
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        await resp.prepare(request)
        self.metrics.sse_started(surface)
        try:
            idle = 0.0
            since_keepalive = 0.0
            while idle < self._SSE_IDLE_END_S:
                frames = await run_sync(request, fetch)
                if frames:
                    idle = 0.0
                    since_keepalive = 0.0
                    write_t0 = time.monotonic()
                    for rowid, row, *name in frames:
                        kind = (name[0] if name else event_name) or ""
                        chunk = f"id: {rowid}\n"
                        if kind:
                            chunk += f"event: {kind}\n"
                        chunk += f"data: {json.dumps(row, default=str)}\n\n"
                        await resp.write(chunk.encode())
                    self.metrics.sse_rows_delivered(surface, len(frames))
                    self.metrics.sse_write_lag(
                        surface, time.monotonic() - write_t0)
                else:
                    if live is not None:
                        if not await run_sync(request, live):
                            break
                        # a LIVE op holds its stream open however long
                        # a compile/step goes quiet — the idle end is
                        # for streams with no liveness signal (events),
                        # never a watch on running work
                        idle = 0.0
                    else:
                        idle += self._SSE_POLL_S
                    since_keepalive += self._SSE_POLL_S
                    if since_keepalive >= self._SSE_KEEPALIVE_S:
                        since_keepalive = 0.0
                        await resp.write(b": keep-alive\n\n")
                    await asyncio.sleep(self._SSE_POLL_S)
            if callable(end_payload):
                # resolved at END time, so the payload reports the final
                # status/cursor, not the stream-open snapshot
                end_payload = await run_sync(request, end_payload)
            await resp.write(
                b"event: end\ndata: "
                + json.dumps(end_payload or {}, default=str).encode()
                + b"\n\n")
        finally:
            self.metrics.sse_finished(surface)
        return resp

    async def all_events(self, request):
        """The platform event surface, two forms:

        * the legacy cross-cluster activity FEED (no stream params):
          newest-first rows scoped to the caller's visibility, `total`
          riding along so a truncated feed says so — unchanged for the
          console.
        * the event STREAM (`?follow=1`, or any of kind/tenant/cluster/
          after): bus rows in rowid order with `Last-Event-ID` resume —
          a dropped console replays nothing and misses nothing, because
          the cursor is the sqlite rowid every row carries as its SSE
          `id:` line (docs/observability.md "Events and live
          telemetry")."""
        from kubeoperator_tpu.utils.errors import ValidationError

        query = request.query
        streaming = (query.get("follow") == "1"
                     or any(query.get(k) for k in
                            ("kind", "tenant", "cluster", "after")))
        if streaming:
            params = await run_sync(request, self._event_stream_params,
                                    request)
            cursor = {"after": params.pop("after")}

            def fetch():
                rows, cursor["after"] = self.s.repos.events.since(
                    cursor["after"], **params)
                return [(rowid, self._event_row(rowid, e), e.kind or
                         "event") for rowid, e in rows]

            if query.get("follow") == "1":
                return await self._sse_follow(
                    request, fetch, surface="events",
                    end_payload=lambda: {"cursor": cursor["after"]})
            rows = await run_sync(request, fetch)
            return json_response({
                "events": [row for _id, row, _kind in rows],
                "cursor": cursor["after"],
            })

        user = request["user"]
        try:
            limit = int(query.get("limit", "500") or 500)
        except ValueError:
            raise ValidationError("limit must be an integer")
        limit = max(1, min(limit, 2000))

        def gather():
            clusters = self._visible_clusters(user)
            names = {c.id: c.name for c in clusters}
            events = self.s.repos.events.find_recent(names, limit)
            total = self.s.repos.events.count_for(names)
            rows = []
            for e in events:
                row = e.to_public_dict()
                row["cluster"] = names.get(e.cluster_id, "")
                rows.append(row)
            return {"events": rows, "total": total}

        return json_response(await run_sync(request, gather))

    async def workload_metrics(self, request):
        """Per-step training telemetry for one workload op: the JSON
        tail past `?after=<rowid>`, or — with `?follow=1` — an SSE
        stream of samples that ends (event: end, carrying the op's
        terminal status) once the run closes. The live console behind
        `koctl workload watch`."""
        from kubeoperator_tpu.utils.errors import ValidationError

        op_ref = request.match_info["op"]
        raw = request.headers.get("Last-Event-ID",
                                  request.query.get("after", "0")) or "0"
        try:
            after = max(int(raw), 0)
        except ValueError:
            raise ValidationError("metrics cursor must be an integer "
                                  "rowid")
        if request.query.get("follow") != "1":
            return json_response(await run_sync(
                request, self.s.workloads.metrics, op_ref, after))
        op = await run_sync(request, self.s.workloads.resolve, op_ref)
        cursor = {"after": after}

        def fetch():
            rows, cursor["after"] = self.s.repos.metric_samples.since(
                op.id, cursor["after"])
            return [(rowid, {
                "step": s.step, "kind": s.kind, "loss": s.loss,
                "step_s": s.step_s, "steps_per_s": s.steps_per_s,
                "tflops": s.tflops, "mfu_pct": s.mfu_pct,
                "attrs": dict(s.attrs), "ts": s.created_at,
            }, "sample") for rowid, s in rows]

        def live():
            return self.s.repos.operations.get(op.id).open

        def end_payload():
            return {"status": self.s.repos.operations.get(op.id).status,
                    "cursor": cursor["after"]}

        return await self._sse_follow(request, fetch, live=live,
                                      end_payload=end_payload,
                                      surface="metrics")

    async def cluster_trace(self, request):
        """Create-to-Ready wall-clock summary (SURVEY.md §5.1: the
        BASELINE metric is a span over the adm phases). Since the span
        store landed this is the THIN view: phase-level rows from the
        condition spans plus a pointer at the newest operation's full
        five-level tree (`/operations/{id}/trace`, `koctl trace`)."""
        def gather():
            cluster = self.s.clusters.get(request.match_info["name"])
            ops = self.s.journal.history(cluster.id, 1)
            latest = ops[0] if ops else None
            return {
                "cluster": cluster.name,
                **cluster.status.trace(),
                "latest_operation": (
                    {"id": latest.id, "kind": latest.kind,
                     "status": latest.status, "trace_id": latest.trace_id,
                     "trace": f"/api/v1/clusters/{cluster.name}"
                              f"/operations/{latest.id}/trace"}
                    if latest is not None else None),
            }

        return json_response(await run_sync(request, gather))

    async def operation_trace(self, request):
        """The full five-level span tree of ONE journal operation
        (operation → phase → attempt → task → host), self-time and the
        critical path annotated — what `koctl trace` renders."""
        from kubeoperator_tpu.observability import span_tree

        def gather():
            cluster = self.s.clusters.get(request.match_info["name"])
            op = self.s.journal.operation(request.match_info["op"])
            if op.cluster_id != cluster.id:
                raise NotFoundError(kind="operation",
                                    name=request.match_info["op"])
            tree = span_tree(self.s.journal.spans_of(op.id))
            return {
                "cluster": cluster.name,
                "operation": op.id,
                "kind": op.kind,
                "status": op.status,
                "trace_id": op.trace_id,
                "tree": tree,
            }

        return json_response(await run_sync(request, gather))

    async def sync_cluster_events(self, request):
        from kubeoperator_tpu.adm import AdmContext

        def sync():
            cluster = self.s.clusters.get(request.match_info["name"])
            inv = AdmContext.for_cluster(self.s.repos, cluster).inventory()
            return self.s.events.sync_from_cluster(
                cluster, self.s.executor, inv
            )

        imported = await run_sync(request, sync)
        return json_response({"imported": imported})

    # ---- infra CRUD ----
    def _crud_routes(self, app, path, service, entity_cls, fields):
        async def list_(request):
            items = await run_sync(request, service.list)
            return json_response([i.to_public_dict() for i in items])

        async def create(request):
            body = await request.json()
            obj = entity_cls(**{k: body[k] for k in fields if k in body})
            item = await run_sync(request, service.create, obj)
            return json_response(item.to_public_dict(), status=201)

        async def get(request):
            item = await run_sync(request, service.get,
                                  request.match_info["name"])
            return json_response(item.to_public_dict())

        async def delete(request):
            await run_sync(request, service.delete, request.match_info["name"])
            return json_response({"ok": True})

        app.router.add_get(path, list_)
        app.router.add_post(path, admin_guard(create))
        app.router.add_get(path + "/{name}", get)
        app.router.add_delete(path + "/{name}", admin_guard(delete))

    # ---- hosts / plans extras ----
    async def register_host(self, request):
        body = await request.json()
        host = await run_sync(request, self.s.hosts.register,
                              body["name"], body["ip"], body["credential"],
                              body.get("port", 22))
        return json_response(host.to_public_dict(), status=201)

    async def host_facts(self, request):
        host = await run_sync(request, self.s.hosts.gather_facts,
                              request.match_info["name"])
        return json_response(host.to_public_dict())

    async def tpu_catalog(self, request):
        return json_response(await run_sync(request, self.s.plans.tpu_catalog))

    # ---- projects ----
    async def list_projects(self, request):
        projects = await run_sync(request, self.s.projects.list)
        return json_response([p.to_public_dict() for p in projects])

    async def create_project(self, request):
        _require_admin(request)
        body = await request.json()
        project = await run_sync(request, self.s.projects.create,
                                 body["name"], body.get("description", ""))
        return json_response(project.to_public_dict(), status=201)

    async def add_member(self, request):
        _require_admin(request)
        body = await request.json()
        member = await run_sync(request, self.s.projects.add_member,
                                request.match_info["name"], body["user"],
                                body.get("role", Role.VIEWER.value))
        return json_response(member.to_public_dict(), status=201)

    # ---- messages ----
    async def inbox(self, request):
        msgs = await run_sync(request, self.s.messages.inbox,
                              request["user"].id,
                              request.query.get("unread") == "1")
        return json_response([m.to_public_dict() for m in msgs])


def create_app(services: Services) -> web.Application:
    app = web.Application(middlewares=[error_middleware, auth_middleware])
    app[SERVICES_KEY] = services
    h = Handlers(services)
    app[METRICS_KEY] = h.metrics

    r = app.router
    r.add_get("/healthz", h.healthz)
    r.add_get("/metrics", h.metrics_endpoint)
    r.add_get("/api/v1/version", h.version)
    r.add_post("/api/v1/auth/login", h.login)
    r.add_post("/api/v1/auth/logout", h.logout)
    r.add_post("/api/v1/auth/password", h.change_password)
    r.add_get("/api/v1/auth/whoami", h.whoami)
    r.add_get("/api/v1/users", h.list_users)
    r.add_post("/api/v1/users", h.create_user)
    r.add_post("/api/v1/ldap/test", h.ldap_test)
    r.add_post("/api/v1/ldap/sync", h.ldap_sync)
    r.add_get("/api/v1/audit", h.audit_log)
    r.add_get("/api/v1/bundle-manifest", h.bundle_manifest_view)
    r.add_get("/api/v1/analysis", h.analysis_report)
    r.add_get("/api/v1/db/stats", h.db_stats)

    view, manage = Role.VIEWER, Role.MANAGER
    r.add_get("/api/v1/clusters", h.list_clusters)
    r.add_post("/api/v1/clusters", h.create_cluster)
    r.add_get("/api/v1/clusters/{name}", cluster_guard(h.get_cluster, view))
    r.add_delete("/api/v1/clusters/{name}",
                 cluster_guard(h.delete_cluster, manage))
    r.add_get("/api/v1/clusters/{name}/status",
              cluster_guard(h.cluster_status, view))
    r.add_post("/api/v1/clusters/import", h.import_cluster)
    r.add_post("/api/v1/clusters/{name}/scale-slices",
               cluster_guard(h.scale_slices, manage))
    r.add_post("/api/v1/clusters/{name}/replace-slice",
               cluster_guard(h.replace_slice, manage))
    r.add_get("/api/v1/clusters/{name}/slices",
              cluster_guard(h.cluster_slices, view))
    r.add_post("/api/v1/clusters/{name}/retry",
               cluster_guard(h.retry_cluster, manage))
    r.add_get("/api/v1/clusters/{name}/kubeconfig",
              cluster_guard(h.cluster_kubeconfig, manage))
    r.add_get("/api/v1/clusters/{name}/logs",
              cluster_guard(h.cluster_logs, view))
    r.add_get("/api/v1/clusters/{name}/nodes",
              cluster_guard(h.list_nodes, view))
    r.add_post("/api/v1/clusters/{name}/nodes",
               cluster_guard(h.scale_up, manage))
    r.add_delete("/api/v1/clusters/{name}/nodes/{node}",
                 cluster_guard(h.scale_down, manage))
    r.add_post("/api/v1/clusters/{name}/upgrade",
               cluster_guard(h.upgrade, manage))
    r.add_post("/api/v1/clusters/{name}/rotate-encryption",
               cluster_guard(h.rotate_encryption, manage))
    r.add_post("/api/v1/clusters/{name}/renew-certs",
               cluster_guard(h.renew_certs, manage))
    r.add_post("/api/v1/clusters/{name}/etcd-maintenance",
               cluster_guard(h.etcd_maintenance, manage))
    r.add_post("/api/v1/clusters/{name}/backup",
               cluster_guard(h.run_backup, manage))
    r.add_get("/api/v1/clusters/{name}/backups",
              cluster_guard(h.list_backups, view))
    r.add_post("/api/v1/clusters/{name}/restore",
               cluster_guard(h.restore, manage))
    r.add_post("/api/v1/clusters/{name}/app-backup",
               cluster_guard(h.app_backup, manage))
    r.add_post("/api/v1/clusters/{name}/app-restore",
               cluster_guard(h.app_restore, manage))
    r.add_get("/api/v1/clusters/{name}/backup-strategy",
              cluster_guard(h.backup_strategy, view))
    r.add_post("/api/v1/clusters/{name}/backup-strategy",
               cluster_guard(h.backup_strategy, manage))
    r.add_get("/api/v1/clusters/{name}/health",
              cluster_guard(h.health, view))
    r.add_get("/api/v1/clusters/{name}/operations",
              cluster_guard(h.cluster_operations, view))
    r.add_post("/api/v1/clusters/{name}/recover",
               cluster_guard(h.recover, manage))
    r.add_get("/api/v1/watchdog", admin_guard(h.watchdog_status))
    r.add_post("/api/v1/watchdog/{name}/reset",
               admin_guard(h.watchdog_reset))
    # fleet rollouts are platform-level operations (they touch many
    # clusters across projects), so the whole surface is admin-gated
    r.add_post("/api/v1/fleet/upgrade", admin_guard(h.fleet_upgrade))
    r.add_get("/api/v1/fleet/drift", admin_guard(h.fleet_drift))
    r.add_get("/api/v1/fleet/converge", admin_guard(h.fleet_converge_status))
    r.add_post("/api/v1/fleet/converge", admin_guard(h.fleet_converge_run))
    r.add_get("/api/v1/fleet/operations", admin_guard(h.fleet_operations))
    r.add_get("/api/v1/fleet/operations/{op}",
              admin_guard(h.fleet_operation))
    r.add_post("/api/v1/fleet/operations/{op}/pause",
               admin_guard(h.fleet_pause))
    r.add_post("/api/v1/fleet/operations/{op}/resume",
               admin_guard(h.fleet_resume))
    r.add_post("/api/v1/fleet/operations/{op}/abort",
               admin_guard(h.fleet_abort))
    r.add_post("/api/v1/workloads/train", admin_guard(h.workload_train))
    r.add_post("/api/v1/workloads/queue", admin_guard(h.workload_submit))
    r.add_get("/api/v1/workloads/queue", admin_guard(h.workload_queue))
    r.add_get("/api/v1/workloads/queue/{entry}",
              admin_guard(h.workload_queue_entry))
    r.add_post("/api/v1/workloads/queue/{entry}/cancel",
               admin_guard(h.workload_queue_cancel))
    r.add_get("/api/v1/workloads/checkpoints",
              admin_guard(h.workload_checkpoints))
    r.add_get("/api/v1/workloads/operations",
              admin_guard(h.workload_operations))
    r.add_get("/api/v1/workloads/operations/{op}",
              admin_guard(h.workload_operation))
    r.add_get("/api/v1/workloads/operations/{op}/trace",
              admin_guard(h.workload_trace))
    r.add_get("/api/v1/workloads/operations/{op}/metrics",
              admin_guard(h.workload_metrics))
    r.add_get("/api/v1/fleet/operations/{op}/trace",
              admin_guard(h.fleet_trace))
    r.add_get("/api/v1/clusters/{name}/components",
              cluster_guard(h.list_components, view))
    r.add_post("/api/v1/clusters/{name}/components",
               cluster_guard(h.install_component, manage))
    r.add_delete("/api/v1/clusters/{name}/components/{component}",
                 cluster_guard(h.uninstall_component, manage))
    r.add_get("/api/v1/events", h.all_events)
    r.add_get("/api/v1/clusters/{name}/events",
              cluster_guard(h.cluster_events, view))
    r.add_post("/api/v1/clusters/{name}/events/sync",
               cluster_guard(h.sync_cluster_events, manage))
    r.add_get("/api/v1/clusters/{name}/trace",
              cluster_guard(h.cluster_trace, view))
    r.add_get("/api/v1/clusters/{name}/operations/{op}/trace",
              cluster_guard(h.operation_trace, view))
    r.add_post("/api/v1/clusters/{name}/cis-scans",
               cluster_guard(h.run_cis_scan, manage))
    r.add_get("/api/v1/clusters/{name}/cis-scans",
              cluster_guard(h.list_cis_scans, view))
    r.add_get("/api/v1/clusters/{name}/cis-scans/{scan}",
              cluster_guard(h.get_cis_scan, view))
    r.add_delete("/api/v1/clusters/{name}/cis-scans/{scan}",
                 cluster_guard(h.delete_cis_scan, manage))
    r.add_post("/api/v1/clusters/{name}/terminal",
               cluster_guard(h.open_terminal, manage))
    r.add_get("/api/v1/terminal", h.list_terminals)
    r.add_post("/api/v1/terminal/{session}/input", h.terminal_input)
    r.add_get("/api/v1/terminal/{session}/output", h.terminal_output)
    r.add_post("/api/v1/terminal/{session}/resize", h.terminal_resize)
    r.add_delete("/api/v1/terminal/{session}", h.close_terminal)

    r.add_get("/api/v1/backup-accounts", h.list_backup_accounts)
    r.add_post("/api/v1/backup-accounts", admin_guard(h.create_backup_account))
    r.add_post("/api/v1/backup-accounts/{name}/test",
               admin_guard(h.test_backup_account))

    h._crud_routes(app, "/api/v1/credentials", services.credentials,
                   Credential, ("name", "username", "password",
                                "private_key", "port"))
    h._crud_routes(app, "/api/v1/regions", services.regions, Region,
                   ("name", "provider", "vars"))
    h._crud_routes(app, "/api/v1/zones", services.zones, Zone,
                   ("name", "region_id", "vars", "ip_pool"))
    async def clone_plan(request):
        body = await request.json()
        plan = await run_sync(request, services.plans.clone,
                              request.match_info["name"],
                              str(body.get("name", "")).strip())
        return json_response(plan.to_public_dict(), status=201)

    from kubeoperator_tpu.models.infra import PLAN_FIELDS

    r.add_post("/api/v1/plans/{name}/clone", admin_guard(clone_plan))
    h._crud_routes(app, "/api/v1/plans", services.plans, Plan, PLAN_FIELDS)
    async def list_hosts(request):
        hosts = await run_sync(request, services.hosts.list)
        return json_response([x.to_public_dict() for x in hosts])

    async def delete_host(request):
        await run_sync(request, services.hosts.delete,
                       request.match_info["name"])
        return json_response({"ok": True})

    r.add_get("/api/v1/hosts", list_hosts)
    r.add_post("/api/v1/hosts/register", admin_guard(h.register_host))
    r.add_post("/api/v1/hosts/{name}/facts", admin_guard(h.host_facts))
    r.add_delete("/api/v1/hosts/{name}", admin_guard(delete_host))
    r.add_get("/api/v1/plans-tpu-catalog", h.tpu_catalog)
    r.add_get("/api/v1/components-catalog", h.component_catalog)
    r.add_get("/api/v1/providers-catalog", h.providers_catalog)
    r.add_get("/api/v1/settings/notify", admin_guard(h.get_notify_settings))
    r.add_put("/api/v1/settings/notify",
              admin_guard(h.update_notify_settings))
    r.add_post("/api/v1/settings/notify/test",
               admin_guard(h.test_notify_channel))
    r.add_get("/api/v1/settings/ldap", admin_guard(h.get_ldap_settings))
    r.add_put("/api/v1/settings/ldap", admin_guard(h.update_ldap_settings))

    r.add_get("/api/v1/projects", h.list_projects)
    r.add_post("/api/v1/projects", h.create_project)
    r.add_post("/api/v1/projects/{name}/members", h.add_member)
    r.add_get("/api/v1/messages", h.inbox)

    # static UI (kubeoperator_tpu/ui/) mounted at /
    import os

    ui_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "ui")
    if os.path.isdir(ui_dir):
        async def index(request):
            return web.FileResponse(os.path.join(ui_dir, "index.html"))

        # /ui/logic.js is generated from ui/logic.py (the tested single
        # source of truth for client-side validation) — registered before
        # the static mount so it wins, and cached for the process lifetime.
        from kubeoperator_tpu.ui.transpile import generate_logic_js

        logic_js = generate_logic_js()

        async def logic(request):
            return web.Response(text=logic_js,
                                content_type="application/javascript")

        r.add_get("/", index)
        r.add_get("/ui/logic.js", logic)
        r.add_static("/ui/", ui_dir)
    return app


def run_server(services: Services, host: str = "127.0.0.1",
               port: int = 8080) -> None:
    services.users.ensure_admin()
    services.cron.start()
    app = create_app(services)
    log.info("ko-tpu server listening on http://%s:%d", host, port)
    web.run_app(app, host=host, port=port, print=None)
