"""REST API (SURVEY.md §2.1 row 1a): /api/v1/* over aiohttp, session auth,
SSE task-log streaming (the reference's websocket log viewer equivalent)."""

from kubeoperator_tpu.api.server import create_app, run_server

__all__ = ["create_app", "run_server"]
