"""Span — one timed node of an operation's trace tree (docs/observability.md).

The span tree is the drill-down the journal (models/operation.py) cannot
give: an Operation row says *what* ran and how far it got; its spans say
where the wall-clock went, five levels deep::

    operation            (root; span id == the journal operation id)
      └── phase          (one per adm phase the engine entered)
            └── attempt  (one per executor attempt, retries are siblings)
                  └── task        (the executor run, possibly remote)
                        └── host  (per-host recap of that run)

Spans are persisted rows (migration 006), keyed by the owning journal
operation id — a trace survives the controller that produced it, and a
crash mid-operation leaves the spans recorded so far (status Running)
as evidence of where it died.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity


class SpanKind:
    """The five levels of the tree, outermost first. WAVE sits outside the
    per-cluster ladder: a fleet rollout's wave spans group child-operation
    trees under the fleet op — a distinct kind so wave wall-clock (the sum
    of many cluster upgrades) can never leak into the adm-phase duration
    histogram, which selects spans by kind."""

    OPERATION = "operation"
    WAVE = "wave"           # fleet rollouts only: fleet op → wave → child op
    WINDOW = "window"       # workload ops only: op → step-window (compile /
    #                         steps) — like WAVE, a distinct kind so tenant
    #                         workload wall-clock never leaks into the
    #                         adm-phase duration histogram
    PHASE = "phase"
    ATTEMPT = "attempt"
    TASK = "task"
    HOST = "host"

    ORDER = (OPERATION, PHASE, ATTEMPT, TASK, HOST)


class SpanStatus:
    RUNNING = "Running"   # started, not finished (or the owner crashed)
    OK = "OK"
    FAILED = "Failed"


@dataclass
class Span(Entity):
    """One trace node. `attrs` carries level-specific facts: FailureKind +
    rc + attempt count on attempt/task spans, the ansible recap numbers on
    host spans — never secrets (attrs surface verbatim over the API)."""

    trace_id: str = ""      # one id per operation; propagated over the RPC
    parent_id: str = ""     # "" = root (the operation span)
    op_id: str = ""         # owning journal operation (migration 005 row)
    cluster_id: str = ""
    name: str = ""          # phase name / playbook / host name
    kind: str = SpanKind.PHASE
    status: str = SpanStatus.RUNNING
    started_at: float = 0.0
    finished_at: float = 0.0
    attrs: dict = field(default_factory=dict)

    @property
    def duration_s(self) -> float:
        if self.started_at and self.finished_at:
            return self.finished_at - self.started_at
        return 0.0
