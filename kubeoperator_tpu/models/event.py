"""Events, messages, and streamed task logs.

Parity: the reference persists per-operation ansible output (kobe
`WatchResult` streams) for the UI log viewer, raises cluster events, and
fans out notifications through a message center (email/webhook)
(SURVEY.md §5.1, §1).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity


@dataclass
class Event(Entity):
    """Cluster-scoped audit/event row (create started, phase failed, backup
    done, health degraded, smoke test result...)."""

    cluster_id: str = ""
    type: str = "Normal"       # Normal | Warning
    reason: str = ""           # stable machine-readable reason code
    message: str = ""          # human text (pre-localized by i18n at read time)


@dataclass
class AuditRecord(Entity):
    """Operation audit row: WHO did WHAT against the platform API (the
    reference ships an operation-log screen; multi-tenant platforms need
    who-deleted-that-cluster answerable). Written by the API layer for
    every mutating request; request BODIES are never recorded — they can
    carry credentials."""

    user_name: str = "-"       # "-" = unauthenticated (e.g. failed login)
    method: str = ""           # POST | PUT | DELETE
    path: str = ""             # /api/v1/... as requested
    status: int = 0            # final HTTP status (after error mapping)
    remote: str = ""           # peer address


@dataclass
class Message(Entity):
    """Message-center notification to a user (in-app; email/webhook senders
    attach via service/message.py subscriptions)."""

    user_id: str = ""
    title: str = ""
    content: str = ""
    level: str = "info"        # info | warning | error
    read: bool = False


@dataclass
class Setting(Entity):
    """One named system-settings document (e.g. 'notify') — the
    runtime-editable configuration tier above app.yaml (SURVEY.md §5.6;
    the reference keeps system settings in a DB table behind an admin
    UI). Secrets inside vars are masked per-key by the owning service's
    public view, not here — which keys are secret is domain knowledge."""

    name: str = ""
    vars: dict = field(default_factory=dict)


@dataclass
class TaskLogChunk(Entity):
    """One streamed chunk of executor output for a (cluster, task) pair —
    the persistence behind the UI live log viewer and `koctl logs`."""

    cluster_id: str = ""
    task_id: str = ""
    seq: int = 0
    line: str = ""
