"""Events, messages, and streamed task logs.

Parity: the reference persists per-operation ansible output (kobe
`WatchResult` streams) for the UI log viewer, raises cluster events, and
fans out notifications through a message center (email/webhook)
(SURVEY.md §5.1, §1).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity


@dataclass
class Event(Entity):
    """One durable platform-telemetry event (docs/observability.md
    "Events and live telemetry").

    Grown (migration 013) from the cluster-scoped UI timeline row into
    the event BUS record: every journal transition, watchdog escalation,
    fencing rejection, slice incident, queue state change and fleet wave
    verdict lands one of these, written in the SAME transaction as the
    state change it describes and streamed over `GET /api/v1/events`
    (sqlite rowid = the SSE cursor). Legacy timeline rows are bus events
    with an empty `kind`."""

    cluster_id: str = ""
    type: str = "Normal"       # Normal | Warning
    reason: str = ""           # stable machine-readable reason code
    message: str = ""          # human text (pre-localized by i18n at read time)
    # live-telemetry bus fields (migration 013); "" = legacy timeline row
    kind: str = ""             # stream key ("op.open", "queue.preempt", ...)
    op_id: str = ""            # owning journal operation, when one exists
    trace_id: str = ""         # the op's trace — joins straight to koctl trace
    tenant: str = ""           # tenant namespace for workload/queue events
    payload: dict = field(default_factory=dict)   # structured facts, never secrets


@dataclass
class MetricSample(Entity):
    """One per-step training telemetry point of a workload operation
    (migration 013): fed from the train loop's on_step seam, buffered on
    the op's tracer and flushed with the span buffer, ring-bounded per op
    (`observability.max_samples_per_op` keeps the NEWEST rows). `kind`
    distinguishes step samples from checkpoint-save markers."""

    op_id: str = ""
    step: int = 0
    kind: str = "step"         # step | checkpoint
    tenant: str = ""
    loss: float = 0.0
    step_s: float = 0.0        # wall-clock of this step (0 on markers)
    steps_per_s: float = 0.0
    tflops: float = 0.0        # achieved model TFLOP/s (0 = unknown)
    mfu_pct: float = 0.0       # 0 = no datasheet peak known
    attrs: dict = field(default_factory=dict)


@dataclass
class AuditRecord(Entity):
    """Operation audit row: WHO did WHAT against the platform API (the
    reference ships an operation-log screen; multi-tenant platforms need
    who-deleted-that-cluster answerable). Written by the API layer for
    every mutating request; request BODIES are never recorded — they can
    carry credentials."""

    user_name: str = "-"       # "-" = unauthenticated (e.g. failed login)
    method: str = ""           # POST | PUT | DELETE
    path: str = ""             # /api/v1/... as requested
    status: int = 0            # final HTTP status (after error mapping)
    remote: str = ""           # peer address


@dataclass
class Message(Entity):
    """Message-center notification to a user (in-app; email/webhook senders
    attach via service/message.py subscriptions)."""

    user_id: str = ""
    title: str = ""
    content: str = ""
    level: str = "info"        # info | warning | error
    read: bool = False


@dataclass
class Setting(Entity):
    """One named system-settings document (e.g. 'notify') — the
    runtime-editable configuration tier above app.yaml (SURVEY.md §5.6;
    the reference keeps system settings in a DB table behind an admin
    UI). Secrets inside vars are masked per-key by the owning service's
    public view, not here — which keys are secret is domain knowledge."""

    name: str = ""
    vars: dict = field(default_factory=dict)


@dataclass
class TaskLogChunk(Entity):
    """One streamed chunk of executor output for a (cluster, task) pair —
    the persistence behind the UI live log viewer and `koctl logs`."""

    cluster_id: str = ""
    task_id: str = ""
    seq: int = 0
    line: str = ""
