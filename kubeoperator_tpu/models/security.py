"""CIS security-scan schema (SURVEY.md §1 'Day-2 operations': CIS security
scans via kube-bench).

One `CisScan` row per run: the kube-bench Job's aggregated totals plus the
individual non-passing checks, so the UI/CLI can render a findings table
without storing the full benchmark output.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

CIS_SCAN_STATUSES = ("Running", "Passed", "Warn", "Failed", "Error")


@dataclass
class CisCheck:
    """One non-passing benchmark check (failures and warnings only)."""

    id: str = ""          # benchmark check id, e.g. "1.2.16"
    text: str = ""        # check description
    status: str = ""      # FAIL | WARN
    node: str = ""        # node the finding came from ("" = cluster-wide)
    remediation: str = ""


@dataclass
class CisScan(Entity):
    __nested__ = {"checks": CisCheck}

    cluster_id: str = ""
    policy: str = "cis-1.8"    # benchmark version kube-bench ran
    status: str = "Running"    # Running | Passed | Warn | Failed | Error
    total_pass: int = 0
    total_fail: int = 0
    total_warn: int = 0
    total_info: int = 0
    checks: list = field(default_factory=list)   # non-passing CisChecks
    message: str = ""

    def validate(self) -> None:
        if not self.cluster_id:
            raise ValidationError("cis scan requires a cluster")
        if self.status not in CIS_SCAN_STATUSES:
            raise ValidationError(f"unknown cis scan status {self.status}")

    def grade(self) -> str:
        """Overall result from the totals: any FAIL ⇒ Failed, else any WARN ⇒
        Warn, else Passed."""
        if self.total_fail > 0:
            return "Failed"
        if self.total_warn > 0:
            return "Warn"
        return "Passed"
