"""Checkpoint — the DB index row over one on-disk training checkpoint
(migration 010; the files themselves live under `checkpoint.dir`,
workloads/checkpoint.py owns their format).

A row exists ONLY for complete checkpoints: the workload service inserts
it after the manifest landed (manifest-last is the on-disk completeness
bit, the row is the queryable mirror). `manifest_sha` ties the row to
the exact manifest bytes it indexed, so a directory swapped under a row
fails verification instead of restoring silently-wrong state. Rows whose
directories disappear are marked `swept` at boot rather than deleted —
the journal-grade audit trail ("what did the op checkpoint, and where
did it go") outlives the disk space."""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

# lifecycle: complete (restorable) -> pruned (retention) | swept (dir
# vanished / torn debris removed at boot)
CHECKPOINT_STATUSES: tuple[str, ...] = ("complete", "pruned", "swept")


@dataclass
class Checkpoint(Entity):
    op_id: str = ""          # workload op that saved it (journal join)
    kind: str = "workload-train"
    tenant: str = ""         # namespace: files live under <dir>/<tenant>/,
    #                          retention and resume resolve per tenant
    step: int = 0            # TrainState step counter at save time
    target_steps: int = 0    # the run's intended total (resume math)
    dir: str = ""            # on-disk checkpoint directory
    manifest_sha: str = ""   # sha256 of the manifest this row indexed
    mesh: dict = field(default_factory=dict)   # {axis: length} at save
    total_bytes: int = 0
    status: str = "complete"

    def validate(self) -> None:
        if not self.op_id:
            raise ValidationError("checkpoint needs the owning op_id")
        if not self.dir:
            raise ValidationError("checkpoint needs its directory path")
        if self.step < 0:
            raise ValidationError("checkpoint step must be >= 0")
        if self.status not in CHECKPOINT_STATUSES:
            raise ValidationError(
                f"checkpoint status {self.status!r} not in "
                f"{CHECKPOINT_STATUSES}")
