"""Entity base: UUID PK + timestamps + dict (de)serialization.

Stands in for the reference's GORM `BaseModel` (ID/CreatedAt/UpdatedAt
[upstream — UNVERIFIED]). Serialization is plain dicts so the repository can
persist JSON columns and the API can emit DTOs without a parallel dto/ tree —
one deliberate simplification over the reference's model/dto split.
"""

from __future__ import annotations

import re as _re

# RFC1123 label: lowercase alnum + '-', no edge hyphens, <= 63 chars. ONE
# copy server-side (Cluster + Plan names both become K8s object names and
# TPU-VM instance prefixes); ui/logic.py dns_label_ok mirrors it client-side
# and the parity tests pin the two against each other.
RFC1123_LABEL_RE = _re.compile(r"[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?")


def validate_dns_label(name: str, what: str) -> None:
    from kubeoperator_tpu.utils.errors import ValidationError

    if not RFC1123_LABEL_RE.fullmatch(name or ""):
        raise ValidationError(
            f"{what} {name!r} must be an RFC1123 DNS label"
        )

import dataclasses
from dataclasses import dataclass, field
from typing import Any, ClassVar, Type, TypeVar

from kubeoperator_tpu.utils.ids import new_id, now_ts

T = TypeVar("T", bound="Entity")


@dataclass
class Entity:
    id: str = field(default_factory=new_id)
    created_at: float = field(default_factory=now_ts)
    updated_at: float = field(default_factory=now_ts)

    # Field names redacted by to_public_dict(); subclasses override. The API
    # layer must emit entities ONLY through to_public_dict so credentials,
    # kubeconfigs and password hashes never cross the HTTP boundary.
    __secret_fields__: ClassVar[frozenset[str]] = frozenset()

    def touch(self) -> None:
        self.updated_at = now_ts()

    def to_dict(self) -> dict[str, Any]:
        def convert(v: Any) -> Any:
            if dataclasses.is_dataclass(v) and not isinstance(v, type):
                return {f.name: convert(getattr(v, f.name)) for f in dataclasses.fields(v)}
            if isinstance(v, (list, tuple)):
                return [convert(x) for x in v]
            if isinstance(v, dict):
                return {k: convert(x) for k, x in v.items()}
            return v

        return convert(self)  # type: ignore[return-value]

    def to_public_dict(self) -> dict[str, Any]:
        """to_dict() minus secret fields — the only shape the API may emit."""
        d = self.to_dict()
        for name in type(self).__secret_fields__:
            d.pop(name, None)
        return d

    @classmethod
    def from_dict(cls: Type[T], data: dict[str, Any]) -> T:
        """Rebuild an entity, recursing into nested dataclass fields and
        ignoring unknown keys (forward/backward schema compatibility)."""
        return dataclass_from_dict(cls, data)


def dataclass_from_dict(cls: Type[T], data: dict[str, Any]) -> T:
    """Generic dataclass hydration: nested types come from the class's
    `__nested__` map (field name -> dataclass, applied to dicts and to list
    elements); unknown keys are dropped."""
    nested_map: dict[str, type] = getattr(cls, "__nested__", {})
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name not in data:
            continue
        v = data[f.name]
        nested = nested_map.get(f.name)
        if nested is not None and isinstance(v, dict):
            v = dataclass_from_dict(nested, v)
        elif nested is not None and isinstance(v, list):
            v = [
                dataclass_from_dict(nested, x) if isinstance(x, dict) else x
                for x in v
            ]
        kwargs[f.name] = v
    return cls(**kwargs)
