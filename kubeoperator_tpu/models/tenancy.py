"""Multi-tenancy & auth: projects, members, users, roles (SURVEY.md §1
"Multi-tenancy & auth": projects/workspaces, RBAC admin/manager/viewer,
local users + LDAP).
"""

from __future__ import annotations

import hashlib
import hmac
import os
from dataclasses import dataclass, field
from enum import Enum

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError


class Role(str, Enum):
    ADMIN = "admin"          # platform-wide
    MANAGER = "manager"      # project-scoped write
    VIEWER = "viewer"        # project-scoped read

    @property
    def rank(self) -> int:
        return {"viewer": 0, "manager": 1, "admin": 2}[self.value]

    def allows(self, required: "Role") -> bool:
        return self.rank >= required.rank


def hash_password(password: str, salt: bytes | None = None) -> str:
    salt = salt or os.urandom(16)
    digest = hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 100_000)
    return salt.hex() + "$" + digest.hex()


def verify_password(password: str, stored: str) -> bool:
    try:
        salt_hex, digest_hex = stored.split("$", 1)
    except ValueError:
        return False
    check = hashlib.pbkdf2_hmac(
        "sha256", password.encode(), bytes.fromhex(salt_hex), 100_000
    )
    return hmac.compare_digest(check.hex(), digest_hex)


@dataclass
class User(Entity):
    name: str = ""
    email: str = ""
    password_hash: str = ""
    is_admin: bool = False
    # "local" users authenticate against password_hash; "ldap" users against
    # the configured directory (UserService.login gates on this source field
    # and round-trips to service/ldap.py for a verification bind).
    source: str = "local"
    locale: str = "en-US"
    active: bool = True

    __secret_fields__ = frozenset({"password_hash"})

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("user name required")
        if self.source not in ("local", "ldap"):
            raise ValidationError(f"unknown user source {self.source}")
        if self.source == "local" and not self.password_hash:
            raise ValidationError("local user needs a password")


@dataclass
class Project(Entity):
    """Workspace owning clusters/plans; RBAC is evaluated per-project."""

    name: str = ""
    description: str = ""

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("project name required")


@dataclass
class ProjectMember(Entity):
    project_id: str = ""
    user_id: str = ""
    role: str = Role.VIEWER.value

    def validate(self) -> None:
        Role(self.role)
        if not self.project_id or not self.user_id:
            raise ValidationError("member needs project and user")
