"""IaaS-side schema: Region, Zone, Plan, Host, Credential (SURVEY.md §2.2).

The deploy Plan is what the Terraform layer consumes (provisioner/) and what
`koctl cluster create --plan <name>` names. TPU-first extension
(BASELINE.json): `accelerator="tpu"` plans carry tpu_type/slice_topology/
ici_mesh/num_slices and GCP TPU-VM provider fields as first-class columns —
replacing the reference's boolean "GPU yes/no" component flag.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.parallel.topology import SliceTopology, parse_accelerator_type
from kubeoperator_tpu.utils.errors import ValidationError


class PlanProvider(str, Enum):
    """IaaS providers the Terraform layer has templates for.

    vsphere/openstack/fusioncompute = upstream parity [upstream —
    UNVERIFIED]; gcp_tpu_vm = the north-star addition [BASELINE].
    bare_metal = manual mode (no Terraform; user-registered hosts).
    """

    BARE_METAL = "bare_metal"
    VSPHERE = "vsphere"
    OPENSTACK = "openstack"
    FUSIONCOMPUTE = "fusioncompute"
    GCP_TPU_VM = "gcp_tpu_vm"


@dataclass
class Credential(Entity):
    """SSH credential bound to hosts (reference `pkg/model/credential.go`
    [upstream — UNVERIFIED])."""

    name: str = ""
    username: str = "root"
    # exactly one of password / private_key is set
    password: str = ""
    private_key: str = ""
    port: int = 22

    __secret_fields__ = frozenset({"password", "private_key"})

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("credential name required")
        if bool(self.password) == bool(self.private_key):
            raise ValidationError(
                "credential needs exactly one of password or private_key"
            )


@dataclass
class Region(Entity):
    """Cloud datacenter + provider connection vars."""

    name: str = ""
    provider: str = PlanProvider.GCP_TPU_VM.value
    # provider connection/auth vars, validated against the declared
    # contract in provisioner/providers.py at service save time (the
    # reference stores an opaque blob; opaque is how typos reach the cloud)
    vars: dict = field(default_factory=dict)

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("region name required")
        PlanProvider(self.provider)

    def to_public_dict(self) -> dict:
        """Per-KEY secret masking inside vars: the read API serves region
        rows to view-role users, and vcenter/openstack/fc passwords live
        inside the vars blob, not in a dedicated field __secret_fields__
        could cover."""
        from kubeoperator_tpu.provisioner.providers import (
            secret_region_keys,
        )

        d = super().to_public_dict()
        masked = dict(d.get("vars", {}))
        for key in secret_region_keys(self.provider):
            if masked.get(key):
                masked[key] = "********"
        d["vars"] = masked
        return d


@dataclass
class Zone(Entity):
    """Subnet / resource pool inside a region; owns the VM IP pool."""

    name: str = ""
    region_id: str = ""
    vars: dict = field(default_factory=dict)  # e.g. gcp zone, subnet, rp
    ip_pool: list = field(default_factory=list)  # static IPs for providers that need them

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("zone name required")
        if not self.region_id:
            raise ValidationError("zone must belong to a region")


# The plan schema's settable-field whitelist — the ONE list every surface
# that builds a Plan from user input consumes (REST create route, koctl
# local transport, `koctl lint --plan`). A new Plan field added here reaches
# all of them at once; a field added to the dataclass but not here is
# deliberately not user-settable.
PLAN_FIELDS: tuple[str, ...] = (
    "name", "provider", "region_id", "zone_ids", "master_count",
    "worker_count", "vars", "accelerator", "tpu_type", "slice_topology",
    "num_slices", "tpu_runtime_version",
)


@dataclass
class Plan(Entity):
    """Deploy plan — instance shapes/counts + accelerator topology.

    `vars` carries provider-specific instance shapes (cpu/mem/disk or machine
    types); TPU plans derive machine shapes from the slice topology instead.
    """

    name: str = ""
    provider: str = PlanProvider.BARE_METAL.value
    region_id: str = ""
    zone_ids: list = field(default_factory=list)
    master_count: int = 1
    worker_count: int = 1
    vars: dict = field(default_factory=dict)

    # ---- TPU-first fields (BASELINE north_star) ----
    accelerator: str = "none"          # "none" | "tpu"  (never "gpu" — by design)
    tpu_type: str = ""                 # e.g. "v5e-16", "v5p-64"
    slice_topology: str = ""           # explicit chips-per-axis ICI mesh, e.g.
                                       # "4x4"; empty = derive from tpu_type
    num_slices: int = 1                # >1 => multislice via JobSet
    tpu_runtime_version: str = ""      # override; default from generation

    def has_tpu(self) -> bool:
        return self.accelerator == "tpu"

    def topology(self) -> SliceTopology:
        if not self.has_tpu():
            raise ValidationError(f"plan {self.name} has no TPU accelerator")
        return parse_accelerator_type(
            self.tpu_type,
            ici_mesh=self.slice_topology or None,
            num_slices=self.num_slices,
        )

    def tpu_worker_count(self) -> int:
        """TPU hosts the plan will provision — derived, never user-entered."""
        return self.topology().total_hosts if self.has_tpu() else 0

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("plan name required")
        # The RFC1123 name-format gate lives at the SERVICE boundary
        # (PlanService.create / rename-on-update), not here: plans
        # persisted before the r4 tightening (e.g. "x x") must stay
        # loadable, updatable under their existing name, and usable by
        # cluster create — retroactive schema validation would strand
        # them with no migration path (ADVICE r4).
        provider = PlanProvider(self.provider)
        if self.accelerator not in ("none", "tpu"):
            # "no GPU package in the build" starts at the schema [BASELINE].
            raise ValidationError(
                f"accelerator must be 'none' or 'tpu', got {self.accelerator!r}"
            )
        if self.master_count < 1:
            raise ValidationError("plan needs >= 1 master")
        if self.master_count not in (1, 3, 5):
            raise ValidationError("HA requires 1, 3 or 5 masters")
        if provider is not PlanProvider.BARE_METAL and not self.region_id:
            raise ValidationError("IaaS plans must reference a region")
        if self.has_tpu():
            if provider is not PlanProvider.GCP_TPU_VM:
                raise ValidationError(
                    "TPU plans require the gcp_tpu_vm provider"
                )
            if not self.tpu_type:
                raise ValidationError("TPU plan needs tpu_type (e.g. 'v5e-16')")
            topo = self.topology()  # raises TopologyError on bad topology
            # Workers and slice hosts are the same machines on TPU plans:
            # the plan's worker_count must equal the derived host count
            # (v5e-16 => 4). 0 means "derive for me".
            if self.worker_count not in (0, topo.total_hosts):
                raise ValidationError(
                    f"plan {self.name}: {self.tpu_type} x{self.num_slices} "
                    f"slices need exactly {topo.total_hosts} TPU hosts, "
                    f"worker_count says {self.worker_count}"
                )


@dataclass
class Host(Entity):
    """A machine: user-registered (manual mode) or Terraform-created (plan
    mode). TPU hosts additionally record their slice coordinates."""

    name: str = ""
    ip: str = ""
    port: int = 22
    credential_id: str = ""
    cluster_id: str = ""
    zone_id: str = ""
    status: str = "Pending"   # Pending | Ready | Failed
    # gathered facts
    os: str = ""
    arch: str = "amd64"
    cpu_cores: int = 0
    memory_mb: int = 0
    # ---- TPU placement (empty for non-TPU hosts) ----
    tpu_worker_id: int = -1    # worker index inside its slice (0..hosts-1)
    tpu_slice_id: int = 0
    tpu_chips: int = 0         # chips attached to this host

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("host name required")
        if not self.ip:
            raise ValidationError(f"host {self.name}: ip required")


# the slice-incident lifecycle the pool ledgers, in causal order — shared
# by the drill's assertions and `koctl cluster slices` rendering.
# "notice" is the ISSUE-11 pre-incident entry: a maintenance NOTICE
# arrived ~30 s before the chips vanish, and the checkpoint+drain flow
# ran on the warning instead of after the loss.
SLICE_EVENT_KINDS: tuple[str, ...] = (
    "notice", "detected", "drained", "degraded", "replaced", "restored",
)


@dataclass
class SliceEvent(Entity):
    """One row of the per-slice incident ledger (migration 009): the slice
    pool's durable record of a preemption riding detect → drain → degrade
    → replace → restore (resilience/slicepool.py). Kept separate from the
    operation journal on purpose — an operation is one controller's unit
    of work, while an incident spans the watchdog's detection, the
    replace operation, and the restore verdict, possibly across
    controllers; the op_id column is the join."""

    cluster_id: str = ""
    slice_id: int = 0
    kind: str = ""       # one of SLICE_EVENT_KINDS
    op_id: str = ""      # owning journal operation ("" for detection rows)
    detail: str = ""

    def validate(self) -> None:
        if not self.cluster_id:
            raise ValidationError("slice event needs a cluster_id")
        if self.kind not in SLICE_EVENT_KINDS:
            raise ValidationError(
                f"slice event kind {self.kind!r} not in {SLICE_EVENT_KINDS}"
            )
        if self.slice_id < 0:
            raise ValidationError("slice_id must be >= 0")
