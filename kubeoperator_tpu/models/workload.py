"""QueueEntry — one tenant workload waiting for (or holding) slice-pool
capacity (migration 011; docs/workloads.md "Queue and preemption").

The queue makes workloads first-class tenants instead of ad-hoc `koctl
workload train` invocations: every submission is BOTH a queue row (this
entity — the queryable mirror the scheduler sorts and the metrics gauge
counts) and a platform-scope journal operation (`op_id` — the durable
truth that inherits lease fencing, the boot reconciler, and the span
tree). The row carries the requested gang (mesh → slices), the priority
class, and the tenant name; the scheduler moves it through

    pending → placed → running → done
                  ↘ running → drained → pending   (priority preemption:
                                                   checkpoint+drain, then
                                                   auto-resume)

with `cancelled`/`failed` as the operator/error exits. `preemptions`
ledgers every eviction (who preempted, at which step, which checkpoint
carries the state) so the drill can prove the whole life from rows.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

# strict classes, strict order — the scheduler never compares raw ints
# across releases, it compares these. `scavenger` exists for platform
# housekeeping gangs (the `workload sweep` verb submits there), below
# every tenant class, preemptible-by-everything.
PRIORITY_CLASSES: dict[str, int] = {
    "high": 30,
    "normal": 20,
    "low": 10,
    "scavenger": 0,
}

QUEUE_STATES: tuple[str, ...] = (
    "pending",    # admitted, waiting for its whole gang to fit
    "placed",     # capacity reserved (placement names the slices)
    "running",    # dispatched through WorkloadService
    "drained",    # checkpoint+drained by a preemption; about to re-queue
    "done",       # run finished, entry op closed Succeeded
    "failed",     # run raised / unhealthy, entry op closed Failed
    "cancelled",  # operator cancel (a running entry drains first)
)

# states that hold capacity (their `placement` names real slices)
ACTIVE_STATES: tuple[str, ...] = ("placed", "running")
# terminal states (entry op closed; the row is history)
TERMINAL_STATES: tuple[str, ...] = ("done", "failed", "cancelled")


def priority_of(priority_class: str) -> int:
    """The class's rank, or ValidationError naming the legal classes —
    the one place a priority string becomes a number."""
    try:
        return PRIORITY_CLASSES[priority_class]
    except KeyError:
        raise ValidationError(
            f"priority class {priority_class!r} not in "
            f"{tuple(PRIORITY_CLASSES)}") from None


@dataclass
class QueueEntry(Entity):
    op_id: str = ""            # the entry's journal op (platform scope)
    tenant: str = ""           # checkpoint namespace + accounting label
    kind: str = "train"        # train | serve | sweep | remediation
    priority_class: str = "normal"
    priority: int = 20         # mirrored rank (priority_of at submit)
    state: str = "pending"
    plan: str = ""             # optional deploy-plan pin (train only)
    mesh: str = ""             # requested mesh axis spec text
    steps: int = 0
    mode: str = ""
    devices: int = 0           # mesh device count (gang size source)
    slices_needed: int = 0     # recomputed against the pool per schedule
    placement: list = field(default_factory=list)   # slice ids held
    preemptions: list = field(default_factory=list)  # eviction ledger
    preempted_by: str = ""     # live marker while a drain is in flight
    checkpoint: str = ""       # latest drained checkpoint (resume source)
    run_ops: list = field(default_factory=list)      # child run op ids
    started_at: float = 0.0    # first dispatch (queue-wait metric end)
    finished_at: float = 0.0
    cancel_requested: bool = False   # operator cancel of a running entry:
    #                                  drain first, then `cancelled`
    message: str = ""
    # serving gangs only (docs/workloads.md "Serving"): how many batched
    # requests the server answers before closing, and the per-request
    # latency SLO its tier promises (0 = serve.* config defaults)
    requests: int = 0
    slo_ms: float = 0.0
    # priority aging (queue.aging_after_s): when the entry last promoted
    # a class (0 = never aged; the next deadline counts from created_at),
    # and the promotion ledger [{"from", "to", "at"}] — the audit trail
    # the repo-ordering tests read
    aged_at: float = 0.0
    agings: list = field(default_factory=list)

    def validate(self) -> None:
        priority_of(self.priority_class)
        # `remediation` entries are the convergence controller's ledgered
        # housekeeping (service/converge.py): zero-slice gangs that ride
        # the queue for ordering/audit, never for capacity
        # `serve` entries are latency-class gangs (docs/workloads.md
        # "Serving"): they restore a checkpoint, hold the compiled
        # forward resident, and answer requests — training is preempted
        # before serving ever is (choose_victims orders kinds)
        if self.kind not in ("train", "serve", "sweep", "remediation"):
            raise ValidationError(
                f"queue entry kind {self.kind!r} not in "
                f"('train', 'serve', 'sweep', 'remediation')")
        if self.state not in QUEUE_STATES:
            raise ValidationError(
                f"queue entry state {self.state!r} not in {QUEUE_STATES}")
        if not self.op_id:
            raise ValidationError("queue entry needs its journal op_id")

    @property
    def active(self) -> bool:
        return self.state in ACTIVE_STATES

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES
