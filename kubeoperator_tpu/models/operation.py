"""Operation — one journaled lifecycle operation (the crash-safety record).

The reference platform keeps lifecycle state only on the cluster row, which
makes a controller restart a stranding event: a cluster stuck `Deploying`
with no running goroutine behind it. The operation journal is the durable
"what was in flight" record — opened before a phase loop starts, updated
per phase, closed on success/failure — so the boot reconciler
(service/reconcile.py) can distinguish "operation running elsewhere" from
"operation orphaned by a dead controller" and act.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from kubeoperator_tpu.models.base import Entity


class OperationStatus(str, Enum):
    RUNNING = "Running"          # journal open; a controller claims this op
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"            # closed honestly by the controller
    INTERRUPTED = "Interrupted"  # orphaned open op swept at boot
    # fleet ops only: parked by the operator mid-rollout; resumable state
    # (remaining waves, completed clusters) preserved in `vars`
    PAUSED = "Paused"


@dataclass
class Operation(Entity):
    """One journal row. `kind` is the operation verb ("create",
    "slice-scale", "upgrade", "backup", ...); `phase`/`phase_status` track
    the last adm phase transition seen, so the row always knows how far the
    operation got; `resume_phase` preserves the re-entry point (the first
    pending condition) when the reconciler marks an orphan Interrupted."""

    cluster_id: str = ""
    cluster_name: str = ""       # survives cluster deletion (terminate ops)
    kind: str = ""
    status: str = OperationStatus.RUNNING.value
    phase: str = ""              # last adm phase name seen ("" = pre-phase)
    phase_status: str = ""       # Running | OK | Failed for `phase`
    message: str = ""
    resume_phase: str = ""       # re-entry point preserved on interruption
    vars: dict = field(default_factory=dict)   # op inputs (upgrade target...)
    # fleet linkage (migration 007): a per-cluster op launched by a fleet
    # rollout carries its fleet op's id; "" = a standalone operation
    parent_op_id: str = ""
    # lease fencing (migration 008, resilience/lease.py): the controller
    # replica that claimed this op's resource and the lease epoch the
    # claim was made under. Every later journal/status write re-verifies
    # the epoch is still current; 0 = unfenced (op predates leases, or
    # leasing is off)
    controller_id: str = ""
    lease_epoch: int = 0
    finished_at: float = 0.0
    # observability: the span tree's trace id ("" = op predates tracing or
    # it was disabled); the root span's id is the operation id itself
    trace_id: str = ""
    # constant-cost history (migration 012): a compact JSON digest of the
    # op's vars (counts, never per-cluster detail) maintained by engines
    # that keep large resumable state in vars — mirrored into a real
    # column so history listings and the latest-op poll never hydrate the
    # vars blob. {} = the op carries no digest (most per-cluster ops)
    summary: dict = field(default_factory=dict)

    @property
    def open(self) -> bool:
        return self.status == OperationStatus.RUNNING.value
