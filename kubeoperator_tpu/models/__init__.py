"""Domain model (reference parity: `pkg/model` + `pkg/dto` GORM structs
[upstream — UNVERIFIED], SURVEY.md §2.1 row 1d and §2.2).

The cluster-plan schema is the single most load-bearing structure: Region →
Zone → Plan → Cluster/ClusterSpec → Host/Node/Credential, with
ClusterStatus(Condition) driving UI progress and phase-engine resumability.
TPU-first extension (BASELINE.json): plans carry accelerator/tpu_type/
slice_topology/ici_mesh as first-class fields.
"""

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.models.infra import (
    SLICE_EVENT_KINDS,
    Credential,
    Host,
    Plan,
    PlanProvider,
    Region,
    SliceEvent,
    Zone,
)
from kubeoperator_tpu.models.cluster import (
    Cluster,
    ClusterPhaseStatus,
    ClusterSpec,
    ClusterStatus,
    ClusterStatusCondition,
    Node,
    NodeRole,
    ProvisionMode,
)
from kubeoperator_tpu.models.backup import BackupAccount, BackupFile, BackupStrategy
from kubeoperator_tpu.models.tenancy import Project, ProjectMember, Role, User
from kubeoperator_tpu.models.event import (
    AuditRecord,
    Event,
    Message,
    MetricSample,
    Setting,
    TaskLogChunk,
)
from kubeoperator_tpu.models.checkpoint import CHECKPOINT_STATUSES, Checkpoint
from kubeoperator_tpu.models.component import ClusterComponent
from kubeoperator_tpu.models.workload import (
    ACTIVE_STATES,
    PRIORITY_CLASSES,
    QUEUE_STATES,
    TERMINAL_STATES,
    QueueEntry,
    priority_of,
)
from kubeoperator_tpu.models.operation import Operation, OperationStatus
from kubeoperator_tpu.models.security import CisCheck, CisScan
from kubeoperator_tpu.models.span import Span, SpanKind, SpanStatus

__all__ = [
    "Entity",
    "Region", "Zone", "Plan", "PlanProvider", "Host", "Credential",
    "SliceEvent", "SLICE_EVENT_KINDS",
    "Cluster", "ClusterSpec", "ClusterStatus", "ClusterStatusCondition",
    "ClusterPhaseStatus", "Node", "NodeRole", "ProvisionMode",
    "BackupAccount", "BackupFile", "BackupStrategy",
    "Project", "ProjectMember", "Role", "User",
    "AuditRecord", "Event", "Message", "MetricSample", "Setting",
    "TaskLogChunk",
    "ClusterComponent",
    "Checkpoint", "CHECKPOINT_STATUSES",
    "QueueEntry", "PRIORITY_CLASSES", "QUEUE_STATES", "ACTIVE_STATES",
    "TERMINAL_STATES", "priority_of",
    "Operation", "OperationStatus",
    "CisCheck", "CisScan",
    "Span", "SpanKind", "SpanStatus",
]
