"""Installable per-cluster components/addons (SURVEY.md §2.1 row 9).

Reference set: prometheus, grafana, loki/logging, ingress controllers,
metrics-server, gpu. The TPU build replaces `gpu` with `tpu` (device plugin +
JobSet + smoke workload) and forbids GPU components entirely [BASELINE].
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

# name -> (playbook that installs it, default vars)
COMPONENT_CATALOG: dict[str, dict] = {
    "prometheus": {"playbook": "component-prometheus.yml", "vars": {}},
    "grafana": {"playbook": "component-grafana.yml", "vars": {"tpu_dashboards": True}},
    "loki": {"playbook": "component-loki.yml", "vars": {}},
    "metrics-server": {"playbook": "component-metrics-server.yml", "vars": {}},
    "ingress-nginx": {"playbook": "component-ingress-nginx.yml", "vars": {}},
    "traefik": {"playbook": "component-traefik.yml", "vars": {}},
    "nfs-provisioner": {
        "playbook": "component-nfs-provisioner.yml",
        "vars": {"nfs_server": "", "nfs_path": "/export",
                 "storage_class_name": "nfs-client"},
        # empty nfs.server deploys a provisioner that can never bind a PV —
        # fail at install time instead
        "required": ("nfs_server",),
    },
    "rook-ceph": {
        "playbook": "component-rook-ceph.yml",
        "vars": {"ceph_use_all_devices": True, "ceph_mon_count": 3},
    },
    "istio": {"playbook": "component-istio.yml", "vars": {}},
    "velero": {
        "playbook": "component-velero.yml",
        # velero_* vars resolved from the cluster's BackupAccount at install
        "vars": {"velero_bucket": "velero"},
    },
    # The TPU runtime as a re-installable component (also runs as a create
    # phase for TPU plans): device plugin + JobSet controller + smoke job.
    "tpu-runtime": {"playbook": "16-tpu-runtime.yml", "vars": {}},
}


@dataclass
class ClusterComponent(Entity):
    cluster_id: str = ""
    name: str = ""
    version: str = "bundled"
    vars: dict = field(default_factory=dict)
    status: str = "Pending"    # Pending | Installing | Installed | Failed | Uninstalled
    message: str = ""

    def validate(self) -> None:
        # Checked before catalog membership so GPU-family names get the
        # explicit policy error (and so future catalog additions can never
        # reintroduce one) [BASELINE: "no GPU package in the build"].
        forbidden = ("gpu", "nvidia", "cuda", "nccl")
        if any(t in self.name.lower() for t in forbidden):
            raise ValidationError("GPU components are excluded from this build")
        if self.name not in COMPONENT_CATALOG:
            raise ValidationError(
                f"unknown component {self.name!r} "
                f"(catalog: {sorted(COMPONENT_CATALOG)})"
            )
