"""Installable per-cluster components/addons (SURVEY.md §2.1 row 9).

Reference set: prometheus, grafana, loki/logging, ingress controllers,
metrics-server, gpu. The TPU build replaces `gpu` with `tpu` (device plugin +
JobSet + smoke workload) and forbids GPU components entirely [BASELINE].
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

# name -> install playbook, default vars, and teardown data: the uninstall
# playbook (component-uninstall.yml) consumes "uninstall" as extra-vars —
# helm releases as [release, namespace] pairs, manifest paths to
# kubectl-delete, node files to remove, namespaces to remove last. Keeping
# teardown next to the install definition means a new component can't ship
# install-only.
COMPONENT_CATALOG: dict[str, dict] = {
    "prometheus": {
        "playbook": "component-prometheus.yml", "vars": {},
        "uninstall": {
            "helm": [["prometheus", "monitoring"]],
            "manifests": ["/opt/ko-manifests/tpu-metrics-servicemonitor.yaml"],
        },
    },
    "grafana": {
        "playbook": "component-grafana.yml",
        "vars": {"tpu_dashboards": True},
        "uninstall": {
            "helm": [["grafana", "monitoring"]],
            "manifests": ["/opt/ko-manifests/grafana-tpu-dashboards.yaml"],
        },
    },
    "loki": {
        "playbook": "component-loki.yml", "vars": {},
        "uninstall": {"helm": [["loki", "monitoring"]]},
    },
    "metrics-server": {
        "playbook": "component-metrics-server.yml", "vars": {},
        "uninstall": {"manifests": ["/opt/ko-manifests/metrics-server.yaml"]},
    },
    "ingress-nginx": {
        "playbook": "component-ingress-nginx.yml", "vars": {},
        "uninstall": {"manifests": ["/opt/ko-manifests/ingress-nginx.yaml"]},
    },
    "traefik": {
        "playbook": "component-traefik.yml", "vars": {},
        "uninstall": {"manifests": ["/opt/ko-manifests/traefik.yaml"]},
    },
    "nfs-provisioner": {
        "playbook": "component-nfs-provisioner.yml",
        "vars": {"nfs_server": "", "nfs_path": "/export",
                 "storage_class_name": "nfs-client"},
        # empty nfs.server deploys a provisioner that can never bind a PV —
        # fail at install time instead
        "required": ("nfs_server",),
        # release lives in the install role's `--namespace storage`; the
        # namespace itself is kept — it may hold PVC-backed user data
        "uninstall": {"helm": [["nfs-provisioner", "storage"]]},
    },
    "rook-ceph": {
        "playbook": "component-rook-ceph.yml",
        "vars": {"ceph_use_all_devices": True, "ceph_mon_count": 3},
        "uninstall": {
            # cluster before operator: the operator must still be running to
            # finalize the CephCluster deletion
            "helm": [["rook-ceph-cluster", "rook-ceph"],
                     ["rook-ceph", "rook-ceph"]],
        },
    },
    "istio": {
        "playbook": "component-istio.yml",
        # mtls_mode: PERMISSIVE (migration) | STRICT (locked mesh);
        # injection_namespaces: colon-separated list to label for sidecar
        # injection; ingress gateway optional
        "vars": {"istio_mtls_mode": "PERMISSIVE",
                 "istio_ingress_enabled": False,
                 "istio_injection_namespaces": "default",
                 # colon-separated hosts for the default Gateway (empty =
                 # wildcard '*' — the literal star would trip the
                 # argument-inertness guard); TLS server added when a
                 # credential secret name is set
                 "istio_gateway_hosts": "",
                 "istio_gateway_tls_secret": ""},
        # enum-checked at install: a typo'd mode would only explode at
        # kubectl-apply time on a real cluster (simulation can't catch it)
        "allowed": {"istio_mtls_mode": ("PERMISSIVE", "STRICT")},
        "uninstall": {
            # Gateway/mTLS objects first (the uninstall role orders
            # manifests before charts — chart removal deletes the CRDs),
            # then charts in reverse install order, then labels + namespace
            "manifests": ["/etc/kubernetes/addons/istio-gateway.yaml",
                          "/etc/kubernetes/addons/istio-mtls.yaml"],
            "helm": [["istio-ingressgateway", "istio-system"],
                     ["istiod", "istio-system"],
                     ["istio-base", "istio-system"]],
            "files": ["/etc/kubernetes/addons/istio-gateway.yaml",
                      "/etc/kubernetes/addons/istio-mtls.yaml"],
            # sidecar-injection labels come off the (var-driven) namespaces
            # — the service resolves the list from the component's vars
            "unlabel_var": ["istio_injection_namespaces", "istio-injection"],
            "namespaces": ["istio-system"],
        },
    },
    "velero": {
        "playbook": "component-velero.yml",
        # velero_* vars resolved from the cluster's BackupAccount at install
        "vars": {"velero_bucket": "velero"},
        "uninstall": {"helm": [["velero", "velero"]],
                      "namespaces": ["velero"],
                      # the 0600 S3 credentials file the install role wrote
                      "files": ["/etc/kubernetes/addons/velero-credentials"]},
    },
    # The TPU runtime as a re-installable component (also runs as a create
    # phase for TPU plans): device plugin + JobSet controller + smoke job.
    # Deliberately NO uninstall teardown: removing the device plugin from a
    # live TPU cluster would strand every TPU workload — the runtime goes
    # away with the cluster, not by component uninstall.
    "tpu-runtime": {"playbook": "16-tpu-runtime.yml", "vars": {}},
}


@dataclass
class ClusterComponent(Entity):
    cluster_id: str = ""
    name: str = ""
    version: str = "bundled"
    vars: dict = field(default_factory=dict)
    status: str = "Pending"    # Pending | Installing | Installed | Failed |
                               # Uninstalling | UninstallFailed | Uninstalled
    message: str = ""

    def validate(self) -> None:
        # Checked before catalog membership so GPU-family names get the
        # explicit policy error (and so future catalog additions can never
        # reintroduce one) [BASELINE: "no GPU package in the build"].
        forbidden = ("gpu", "nvidia", "cuda", "nccl")
        if any(t in self.name.lower() for t in forbidden):
            raise ValidationError("GPU components are excluded from this build")
        if self.name not in COMPONENT_CATALOG:
            raise ValidationError(
                f"unknown component {self.name!r} "
                f"(catalog: {sorted(COMPONENT_CATALOG)})"
            )
