"""Cluster, ClusterSpec, Node, ClusterStatus(Condition) (SURVEY.md §2.2).

ClusterStatusCondition is the resumability contract: the phase engine (adm/)
writes exactly one condition row per phase, and a failed create/upgrade/scale
re-enters at the first non-OK condition (SURVEY.md §3.1).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError
from kubeoperator_tpu.utils.ids import now_ts
from kubeoperator_tpu.version import SUPPORTED_K8S_VERSIONS


class ProvisionMode(str, Enum):
    MANUAL = "manual"       # bare-metal: user-registered hosts
    PLAN = "plan"           # IaaS: Terraform provisions from a deploy plan
    IMPORTED = "imported"   # existing cluster managed via kubeconfig only


class NodeRole(str, Enum):
    MASTER = "master"
    WORKER = "worker"


class ClusterPhaseStatus(str, Enum):
    """Lifecycle states surfaced in the UI/API and koctl exit codes."""

    INITIALIZING = "Initializing"
    PROVISIONING = "Provisioning"   # Terraform running (plan mode)
    DEPLOYING = "Deploying"         # adm phases running
    SMOKE_TESTING = "SmokeTesting"  # TPU psum gate (TPU plans only)
    RUNNING = "Running"
    READY = "Ready"
    FAILED = "Failed"
    UPGRADING = "Upgrading"
    SCALING = "Scaling"
    TERMINATING = "Terminating"
    TERMINATED = "Terminated"


class ConditionStatus(str, Enum):
    UNKNOWN = "Unknown"
    RUNNING = "Running"
    OK = "OK"
    FAILED = "Failed"


@dataclass
class ClusterSpec:
    """What to deploy (versions/runtime/CNI/net) — the extra-vars source."""

    k8s_version: str = ""
    runtime: str = "containerd"            # containerd | docker
    cni: str = "calico"                    # calico | flannel | cilium
    ingress: str = "nginx"                 # nginx | traefik | none
    service_cidr: str = "10.96.0.0/16"
    pod_cidr: str = "10.244.0.0/16"
    lb_mode: str = "internal"              # internal haproxy+keepalived | external
    lb_endpoint: str = ""                  # required when lb_mode == external
    kube_proxy_mode: str = "iptables"      # iptables | ipvs
    nodelocaldns_enabled: bool = True      # per-node DNS cache DaemonSet
    helm_enabled: bool = True
    metrics_server_enabled: bool = True
    worker_count: int = 1
    # ---- TPU runtime phase vars (replaces reference GPU flag) ----
    tpu_enabled: bool = False
    tpu_device_plugin_version: str = "v1.0"
    jobset_enabled: bool = False           # multislice launcher
    smoke_test_gbps_threshold: float = 0.0  # 0 = report-only, >0 gates Ready

    def validate(self) -> None:
        if self.k8s_version and self.k8s_version not in SUPPORTED_K8S_VERSIONS:
            raise ValidationError(
                f"k8s_version {self.k8s_version} unsupported "
                f"(bundle ships {', '.join(SUPPORTED_K8S_VERSIONS)})"
            )
        if self.runtime not in ("containerd", "docker"):
            raise ValidationError(f"unknown runtime {self.runtime}")
        if self.cni not in ("calico", "flannel", "cilium"):
            raise ValidationError(f"unknown cni {self.cni}")
        if self.ingress not in ("nginx", "traefik", "none"):
            raise ValidationError(f"unknown ingress {self.ingress}")
        if self.lb_mode not in ("internal", "external"):
            raise ValidationError(f"unknown lb_mode {self.lb_mode}")
        if self.lb_mode == "external" and not self.lb_endpoint:
            raise ValidationError("external lb_mode needs lb_endpoint")
        if self.kube_proxy_mode not in ("iptables", "ipvs"):
            raise ValidationError(
                f"unknown kube_proxy_mode {self.kube_proxy_mode}"
            )
        import ipaddress

        for what, cidr in (("service_cidr", self.service_cidr),
                           ("pod_cidr", self.pod_cidr)):
            try:
                net = ipaddress.ip_network(cidr, strict=False)
            except ValueError as e:
                raise ValidationError(f"{what} {cidr!r} is not a CIDR: {e}")
            if net.num_addresses < 16:
                # the DNS service ClusterIP is the tenth address of the
                # service range; a tighter mask has no room for it
                raise ValidationError(f"{what} {cidr!r} is too small (< /28)")


@dataclass
class ClusterStatusCondition:
    """One row per adm phase; ordered by `order_index`."""

    name: str = ""                              # phase name
    status: str = ConditionStatus.UNKNOWN.value
    message: str = ""
    order_index: int = 0
    started_at: float = 0.0
    finished_at: float = 0.0
    # resilience trail (adm/engine.py retry loop): how many executor
    # attempts this phase consumed, the last failure's TRANSIENT/PERMANENT
    # classification, and the total backoff the retries slept — kept so the
    # create-to-Ready trace stays honest about where wall-clock went
    attempts: int = 0
    classification: str = ""
    backoff_s: float = 0.0

    @property
    def duration_s(self) -> float:
        """Phase wall-clock span — the create-to-Ready trace is the sum of
        these (BASELINE metric 1; SURVEY.md §5.1)."""
        if self.started_at and self.finished_at:
            return self.finished_at - self.started_at
        return 0.0


@dataclass
class ClusterStatus:
    phase: str = ClusterPhaseStatus.INITIALIZING.value
    message: str = ""
    conditions: list = field(default_factory=list)  # [ClusterStatusCondition]
    # smoke-test results (TPU plans)
    smoke_gbps: float = 0.0
    smoke_chips: int = 0
    smoke_passed: bool = False
    # True when the latest gate ran under ko_simulation (demo clusters):
    # fabricated GB/s must never be readable as measured, anywhere the
    # number surfaces (console trend, TPU panel, koctl, events)
    smoke_simulated: bool = False
    # every smoke measurement ever gated on (create, upgrade re-gate, slice
    # scale, guided recovery), newest last, capped — the console's GB/s
    # trend; plain dicts: {ts, gbps, chips, passed, simulated}
    smoke_history: list = field(default_factory=list)

    __nested__ = {"conditions": ClusterStatusCondition}

    def condition(self, name: str) -> ClusterStatusCondition | None:
        for c in self.conditions:
            if c.name == name:
                return c
        return None

    def upsert_condition(
        self, name: str, status: ConditionStatus, message: str = ""
    ) -> ClusterStatusCondition:
        cond = self.condition(name)
        if cond is None:
            cond = ClusterStatusCondition(name=name, order_index=len(self.conditions))
            self.conditions.append(cond)
        if status is ConditionStatus.RUNNING:
            # A retry of a previously-finished/failed phase restarts its span;
            # otherwise duration_s would absorb the idle gap and corrupt the
            # create-to-Ready trace (BASELINE metric 1).
            if cond.status != ConditionStatus.RUNNING.value:
                cond.started_at = now_ts()
                cond.finished_at = 0.0
        if status in (ConditionStatus.OK, ConditionStatus.FAILED):
            if not cond.started_at:
                cond.started_at = now_ts()
            cond.finished_at = now_ts()
        cond.status = status.value
        cond.message = message
        return cond

    def reset_conditions(self, names: list[str]) -> None:
        """Drop the named conditions (start of a fresh run of an operation
        whose previous run completed — see ClusterAdm.run)."""
        keep = [c for c in self.conditions if c.name not in set(names)]
        self.conditions = keep
        for i, c in enumerate(self.conditions):
            c.order_index = i

    def first_unfinished(self) -> str | None:
        """Resume point: first condition that isn't OK (or None if all OK)."""
        for c in sorted(self.conditions, key=lambda c: c.order_index):
            if c.status != ConditionStatus.OK.value:
                return c.name
        return None

    def total_duration_s(self) -> float:
        return sum(c.duration_s for c in self.conditions)

    def trace(self) -> dict:
        """Phase spans as a native trace (SURVEY.md §5.1: the BASELINE
        create-to-Ready metric is a span over the adm phases)."""
        spans = [{
            "name": c.name,
            "status": c.status,
            "started_at": c.started_at,
            "finished_at": c.finished_at,
            "duration_s": round(c.duration_s, 3) if c.duration_s else None,
            "attempts": c.attempts,
            "classification": c.classification or None,
            "backoff_s": round(c.backoff_s, 3) if c.backoff_s else 0.0,
        } for c in sorted(self.conditions, key=lambda c: c.order_index)]
        started = [s["started_at"] for s in spans if s["started_at"]]
        finished = [s["finished_at"] for s in spans if s["finished_at"]]
        return {
            "phase": self.phase,
            "total_s": (round(max(finished) - min(started), 3)
                        if started and finished else None),
            "spans": spans,
        }


# base.py's Entity dataclass ordering requires defaults; ClusterStatus needs a
# factory so each cluster owns its own status object.
@dataclass
class Cluster(Entity):
    name: str = ""
    project_id: str = ""
    provision_mode: str = ProvisionMode.MANUAL.value
    plan_id: str = ""                       # set in plan mode
    spec: ClusterSpec = field(default_factory=ClusterSpec)
    status: ClusterStatus = field(default_factory=ClusterStatus)
    kubeconfig: str = ""                    # stored after deploy; never leaves
    api_endpoint: str = ""                  # the API except via explicit download

    __nested__ = {"spec": ClusterSpec, "status": ClusterStatus}
    __secret_fields__ = frozenset({"kubeconfig"})

    def require_managed(self, operation: str) -> None:
        """Imported clusters are reachable only through their kubeconfig —
        every operation that needs SSH onto the nodes (playbooks, terraform)
        must refuse with a clear reason instead of failing mid-phase."""
        if self.provision_mode == ProvisionMode.IMPORTED.value:
            from kubeoperator_tpu.utils.errors import ValidationError

            raise ValidationError(
                f"cluster {self.name} was imported (kubeconfig-only); "
                f"{operation} requires SSH-managed nodes"
            )

    def validate(self) -> None:
        # shared RFC1123 gate (models/base.py): the name becomes K8s
        # object names and DNS records downstream
        from kubeoperator_tpu.models.base import validate_dns_label

        validate_dns_label(self.name, "cluster name")
        ProvisionMode(self.provision_mode)
        if self.provision_mode == ProvisionMode.PLAN.value and not self.plan_id:
            raise ValidationError("plan-mode cluster must reference a plan")
        self.spec.validate()


@dataclass
class Node(Entity):
    """A host bound into a cluster with a role (reference joins Host↔Cluster
    through Node rows [upstream — UNVERIFIED])."""

    name: str = ""
    cluster_id: str = ""
    host_id: str = ""
    role: str = NodeRole.WORKER.value
    status: str = "Pending"   # Pending | Joining | Ready | Draining | Removed | Failed

    def validate(self) -> None:
        NodeRole(self.role)
        if not self.cluster_id or not self.host_id:
            raise ValidationError("node must bind a cluster and a host")
