"""Backup schema: accounts (object-store endpoints), strategies (cron),
files (snapshots taken). Parity: SURVEY.md §3.5 / §5.4 — etcd snapshot on a
master, uploaded to an S3/OSS/SFTP-style backup account, cron-driven;
restore is the inverse playbook + cluster restart.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.utils.errors import ValidationError

BACKUP_ACCOUNT_TYPES = ("s3", "oss", "sftp", "local")


@dataclass
class BackupAccount(Entity):
    name: str = ""
    type: str = "local"
    bucket: str = ""
    # endpoint/credential vars per type (endpoint, access_key, secret_key,
    # or sftp host/user/key, or local dir)
    vars: dict = field(default_factory=dict)
    status: str = "Valid"

    def validate(self) -> None:
        if not self.name:
            raise ValidationError("backup account name required")
        if self.type not in BACKUP_ACCOUNT_TYPES:
            raise ValidationError(f"unknown backup account type {self.type}")
        if self.type != "local" and not self.bucket:
            raise ValidationError("bucket required for remote backup accounts")


@dataclass
class BackupStrategy(Entity):
    """Per-cluster cron schedule + retention."""

    cluster_id: str = ""
    account_id: str = ""
    cron: str = "0 3 * * *"     # daily 03:00 by default
    save_num: int = 7           # retention count
    enabled: bool = True

    def validate(self) -> None:
        if not self.cluster_id or not self.account_id:
            raise ValidationError("backup strategy needs cluster and account")
        if len(self.cron.split()) != 5:
            raise ValidationError(f"cron {self.cron!r} must have 5 fields")
        if self.save_num < 1:
            raise ValidationError("save_num must be >= 1")


@dataclass
class BackupFile(Entity):
    cluster_id: str = ""
    account_id: str = ""
    name: str = ""              # object key / file name
    size_bytes: int = 0
    status: str = "Created"     # Created | Uploaded | Restored | Failed
    message: str = ""
    # True when the backup role wrote the ko-tpu/backup-sentinel key into
    # etcd before snapshotting — restore verification then REQUIRES the
    # restored keyspace to answer with this file's name. Rows persisted
    # before sentinel support deserialize False and are grandfathered
    # (restore still gates on version/nodes/etcd/apiserver, just not the
    # data sentinel, which their snapshots cannot contain).
    has_sentinel: bool = False
