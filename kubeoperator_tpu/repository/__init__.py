"""Persistence: SQLite + versioned migrations + per-entity repositories.

Parity (SURVEY.md §2.1 rows 1d/1e): the reference persists through GORM over
MySQL with SQL migration files applied at boot. We keep the shape — versioned
migrations in `repository/migrations/*.sql`, one repository per entity — over
SQLite (§7.1 allows SQLite-or-MySQL; SQLite keeps the framework dependency-
free and air-gap friendly, matching the offline-first posture).

Row layout: stable/query columns are real columns; the full entity document
rides a JSON `data` column, so schema migrations are only needed when a
*queried* field changes.
"""

from kubeoperator_tpu.repository.db import Database
from kubeoperator_tpu.repository.repos import Repositories

__all__ = ["Database", "Repositories"]
