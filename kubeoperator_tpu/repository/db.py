"""SQLite database handle + boot-time migration runner.

Reference parity: `migration/` SQL files applied at server boot
[upstream — UNVERIFIED], SURVEY.md §2.1 row 1e. Applied versions are recorded
in `schema_migrations`; files are applied in lexical order inside one
transaction each, so a failed migration leaves the previous version intact.
"""

from __future__ import annotations

import os
import re
import sqlite3
import threading
import time
from contextlib import contextmanager
from typing import Iterator

from kubeoperator_tpu.utils.logging import get_logger

log = get_logger("repository")

MIGRATIONS_DIR = os.path.join(os.path.dirname(__file__), "migrations")
_MIGRATION_RE = re.compile(r"^(\d{3})_[\w-]+\.sql$")

# ---- sanctioned dialect seams (docs/resilience.md "SQL contract") ----
# The ONLY two places SQLite-specific SQL may appear in a statement; the
# KO-S002 dialect rule enforces that every other statement stays ANSI-ish,
# so a Postgres backend (ROADMAP item 1) swaps exactly these expressions.
#
# DB_NOW_SQL — the database's own clock as epoch seconds. Every lease
# comparison and the migration ledger stamp use THIS expression, never a
# replica's time.time(): expiry must mean the same instant to every
# replica sharing the file, whatever their local clocks do.
# Postgres translation: extract(epoch from clock_timestamp()).
DB_NOW_SQL = "(julianday('now') - 2440587.5) * 86400.0"

# ROWID_SQL — the monotonic insertion-order cursor column backing every
# stream read (event bus Last-Event-ID, metric-sample follow, log tails)
# and every same-timestamp tiebreak/prune. SQLite's implicit rowid IS
# that cursor (insertion order == stream order under one writer file).
# Postgres translation: a bigserial column (rowids only grow, so resumed
# cursors replay nothing stale — the contract the SSE feed documents).
ROWID_SQL = "rowid"


class _TxRecorder:
    """The connection facade `tx()` yields while the flight recorder is
    on: each execute/executemany is timed as phase ``exec``, and the
    transaction's lock-wait (the BEGIN IMMEDIATE wall) plus its COMMIT
    wall are attributed to the FIRST statement the tx executed — that
    statement is what the caller was blocked waiting to run. Everything
    else delegates to the real sqlite3 connection, so repo code using
    cursors/lastrowid/total_changes is none the wiser. With the
    `observability.db_telemetry` knob off this class is never
    constructed and tx() yields the raw connection exactly as before."""

    __slots__ = ("_conn", "_telemetry", "first_sql", "_pending_lock_s")

    def __init__(self, conn, telemetry, lock_wait_s: float) -> None:
        self._conn = conn
        self._telemetry = telemetry
        self.first_sql: str | None = None
        self._pending_lock_s = lock_wait_s

    def _note_first(self, sql: str) -> None:
        if self.first_sql is None:
            self.first_sql = sql
            if self._pending_lock_s:
                self._telemetry.observe(sql, "lock_wait",
                                        self._pending_lock_s)
                self._pending_lock_s = 0.0

    def execute(self, sql, *args, **kwargs):
        t0 = time.perf_counter()
        cur = self._conn.execute(sql, *args, **kwargs)
        self._telemetry.observe(sql, "exec", time.perf_counter() - t0)
        self._note_first(sql)
        return cur

    def executemany(self, sql, *args, **kwargs):
        t0 = time.perf_counter()
        cur = self._conn.executemany(sql, *args, **kwargs)
        self._telemetry.observe(sql, "exec", time.perf_counter() - t0)
        self._note_first(sql)
        return cur

    def settle(self, commit_s: float | None = None) -> None:
        """Close the tx's books: attribute the COMMIT wall (and any
        lock-wait a statement never claimed — an empty tx) to the first
        statement, or the `(empty-tx)` pseudo-statement."""
        from kubeoperator_tpu.observability.dbtelemetry import EMPTY_TX

        owner = self.first_sql if self.first_sql is not None else EMPTY_TX
        if self._pending_lock_s:
            self._telemetry.observe(owner, "lock_wait",
                                    self._pending_lock_s)
            self._pending_lock_s = 0.0
        if commit_s is not None:
            self._telemetry.observe(owner, "commit", commit_s)

    def __getattr__(self, name):
        return getattr(self._conn, name)


def statement_is_complete(stmt: str) -> bool:
    """Whether `stmt` is one complete SQL statement (';'-terminated) —
    exposed so the analysis layer's migration rule (KO-X006) can validate
    SQL without importing sqlite3 itself (its own repo-layering rule)."""
    return sqlite3.complete_statement(stmt)


def _split_statements(script: str) -> list[str]:
    """Split a SQL script into complete statements (';'-aware via
    sqlite3.complete_statement, so literals containing ';' survive)."""
    statements: list[str] = []
    buf = ""
    for line in script.splitlines():
        stripped = line.strip()
        if not buf and (not stripped or stripped.startswith("--")):
            continue
        buf += line + "\n"
        if sqlite3.complete_statement(buf):
            statements.append(buf.strip())
            buf = ""
    if buf.strip():
        statements.append(buf.strip())
    return statements


class Database:
    """Process-wide SQLite handle, safe for the server's mixed
    event-loop + worker-thread usage (WAL + serialized access), and for
    MULTI-HANDLE access: N controller replicas (separate Database
    instances, possibly separate processes) share one WAL file for the
    lease-based control plane (docs/resilience.md "Controller leases"), so
    a second writer must queue politely instead of failing immediately —
    `busy_timeout` + BEGIN IMMEDIATE + a bounded locked-retry below."""

    # bounded retry on "database is locked" around BEGIN IMMEDIATE: the
    # busy handler waits busy_timeout_ms per attempt, so the worst case is
    # _LOCKED_RETRIES * busy_timeout before a writer gives up honestly
    _LOCKED_RETRIES = 5
    _LOCKED_BACKOFF_S = 0.05

    def __init__(self, path: str = "ko_tpu.db",
                 synchronous: str = "NORMAL",
                 busy_timeout_ms: int = 5000,
                 telemetry=None) -> None:
        self.path = path
        # the control-plane flight recorder (observability/dbtelemetry.py,
        # `observability.db_telemetry`): None = the recorder layer does
        # not exist and every path below is bit-identical pre-recorder
        self.telemetry = telemetry
        self._lock = threading.RLock()
        self._tx_depth = 0  # nesting depth of tx() scopes (under _lock)
        self._tx_recorder: _TxRecorder | None = None  # live outermost tx
        self._conn = sqlite3.connect(
            path, check_same_thread=False, isolation_level=None
        )
        self._conn.row_factory = sqlite3.Row
        self._conn.execute("PRAGMA journal_mode=WAL")
        # `db.busy_timeout_ms`: how long THIS handle's statements block on
        # another handle's write lock before raising "database is locked".
        # The pre-lease default of 0 made any second writer on the file
        # fail instantly — fatal under multi-replica WAL access.
        self._conn.execute(
            f"PRAGMA busy_timeout={max(int(busy_timeout_ms), 0)}")
        # `db.synchronous` (utils/config.py DEFAULTS): NORMAL is the
        # standard WAL pairing — durability ordering is preserved (WAL is
        # sequential, so a crash can only lose a SUFFIX of commits, never
        # reorder the journal's open-before-phase-flip invariant), and a
        # process crash loses nothing; per-commit fsync under FULL was
        # ~25% of create-to-Ready wall-clock (PERF.md round 11)
        if str(synchronous).upper() not in ("NORMAL", "FULL"):
            raise ValueError(
                f"db.synchronous must be NORMAL or FULL, got {synchronous!r}")
        self._conn.execute(f"PRAGMA synchronous={synchronous.upper()}")
        self._conn.execute("PRAGMA foreign_keys=ON")
        self.migrate()

    @contextmanager
    def tx(self) -> Iterator[sqlite3.Connection]:
        """Serialized transaction scope.

        BEGIN IMMEDIATE, not deferred: the write lock is taken AT BEGIN,
        where the busy handler applies — a deferred tx upgrading to write
        mid-body can hit SQLITE_BUSY(_SNAPSHOT) that no busy_timeout will
        retry, which is exactly the failure interleaved writers on one WAL
        file would see constantly. BEGIN itself gets a bounded retry on
        top of the per-attempt busy_timeout; once BEGIN succeeds the tx
        body owns the write lock and cannot hit "locked" from a peer.

        NESTABLE: an inner tx() under an already-open scope joins the
        outer transaction (the RLock makes the re-entry safe; only the
        outermost frame BEGINs/COMMITs). This is what lets a lease-epoch
        fence check and the journal write it guards commit ATOMICALLY —
        the journal wraps both in one tx() so no peer's CAS takeover can
        land between check and write (resilience/journal.py). An exception
        anywhere inside rolls back the WHOLE outermost transaction; a
        caller that catches an inner failure and keeps writing would
        commit a half-failed tx, so inner frames must let errors
        propagate (the fence's StaleEpochError, a BaseException, does)."""
        with self._lock:
            outermost = self._tx_depth == 0
            if outermost:
                lock_wait_s = self._begin_immediate()
                if self.telemetry is not None:
                    self._tx_recorder = _TxRecorder(
                        self._conn, self.telemetry, lock_wait_s)
            self._tx_depth += 1
            if self.telemetry is not None:
                self.telemetry.note_tx_depth(self._tx_depth)
            conn = (self._tx_recorder if self._tx_recorder is not None
                    else self._conn)
            try:
                yield conn
            except BaseException:
                self._tx_depth -= 1
                if outermost:
                    recorder, self._tx_recorder = self._tx_recorder, None
                    self._conn.execute("ROLLBACK")
                    if recorder is not None:
                        recorder.settle()   # books the unclaimed lock-wait
                raise
            self._tx_depth -= 1
            if outermost:
                recorder, self._tx_recorder = self._tx_recorder, None
                t0 = time.perf_counter()
                self._conn.execute("COMMIT")
                if recorder is not None:
                    recorder.settle(time.perf_counter() - t0)

    def _begin_immediate(self) -> float:
        """BEGIN IMMEDIATE with the bounded locked-retry; returns the
        total wall spent acquiring the write lock (busy-handler waits +
        backoff sleeps + the BEGIN itself) — the tx's lock_wait phase."""
        t0 = time.perf_counter()
        for attempt in range(self._LOCKED_RETRIES):
            try:
                self._conn.execute("BEGIN IMMEDIATE")
                return time.perf_counter() - t0
            except sqlite3.OperationalError as e:
                if "locked" not in str(e) and "busy" not in str(e):
                    raise
                if self.telemetry is not None:
                    self.telemetry.busy_retry()
                if attempt == self._LOCKED_RETRIES - 1:
                    raise
                log.warning(
                    "database %s locked by another writer; retry %d/%d",
                    self.path, attempt + 1, self._LOCKED_RETRIES)
                time.sleep(self._LOCKED_BACKOFF_S * (attempt + 1))
        return time.perf_counter() - t0

    def query(self, sql: str, params: tuple = ()) -> list[sqlite3.Row]:
        if self.telemetry is None:
            with self._lock:
                return list(self._conn.execute(sql, params))
        with self._lock:
            t0 = time.perf_counter()
            rows = list(self._conn.execute(sql, params))
        self.telemetry.observe(sql, "exec", time.perf_counter() - t0)
        return rows

    def execute(self, sql: str, params: tuple = ()) -> None:
        with self.tx() as conn:
            conn.execute(sql, params)

    # ---- migrations ----
    def applied_versions(self) -> set[str]:
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS schema_migrations ("
                " version TEXT PRIMARY KEY, applied_at REAL)"
            )
            rows = self._conn.execute("SELECT version FROM schema_migrations")
            return {r["version"] for r in rows}

    def migrate(self, migrations_dir: str = MIGRATIONS_DIR) -> list[str]:
        applied = self.applied_versions()
        ran: list[str] = []
        for fname in sorted(os.listdir(migrations_dir)):
            m = _MIGRATION_RE.match(fname)
            if not m or m.group(1) in applied:
                continue
            with open(os.path.join(migrations_dir, fname), encoding="utf-8") as f:
                script = f.read()
            # Statement-by-statement inside one explicit tx: SQLite DDL is
            # transactional, and executescript() would auto-COMMIT and break
            # the all-or-nothing guarantee.
            with self.tx() as conn:
                for stmt in _split_statements(script):
                    conn.execute(stmt)
                conn.execute(
                    f"INSERT INTO schema_migrations VALUES (?, {DB_NOW_SQL})",
                    (m.group(1),),
                )
            log.info("applied migration %s", fname)
            ran.append(fname)
        return ran

    def close(self) -> None:
        with self._lock:
            self._conn.close()
