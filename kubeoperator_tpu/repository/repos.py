"""Per-entity repositories (reference parity: `pkg/repository`
[upstream — UNVERIFIED], SURVEY.md §2.1 row 1d).

A generic JSON-document repo supplies CRUD; subclasses pin the table, entity
class, and which entity fields mirror into real query columns.
"""

from __future__ import annotations

import json
from typing import Generic, Iterable, Type, TypeVar

from kubeoperator_tpu.models import (
    AuditRecord,
    BackupAccount,
    BackupFile,
    BackupStrategy,
    Checkpoint,
    CisScan,
    Cluster,
    ClusterComponent,
    Credential,
    Event,
    Host,
    Message,
    MetricSample,
    Node,
    Operation,
    Plan,
    Project,
    ProjectMember,
    QueueEntry,
    Region,
    Setting,
    SliceEvent,
    Span,
    TaskLogChunk,
    User,
    Zone,
)
from kubeoperator_tpu.models.base import Entity
from kubeoperator_tpu.repository.db import DB_NOW_SQL, ROWID_SQL, Database
from kubeoperator_tpu.utils.errors import ConflictError, NotFoundError

E = TypeVar("E", bound=Entity)


class EntityRepo(Generic[E]):
    table: str = ""
    entity: Type[E] = Entity  # type: ignore[assignment]
    # entity attribute -> column name mirrored for querying
    columns: tuple[str, ...] = ("name",)

    def __init__(self, db: Database) -> None:
        self.db = db

    # ---- CRUD ----
    def save(self, obj: E) -> E:
        obj.touch()
        cols = ["id", *self.columns, "data", "created_at", "updated_at"]
        vals = [
            obj.id,
            *[self._column_value(obj, c) for c in self.columns],
            json.dumps(obj.to_dict()),
            obj.created_at,
            obj.updated_at,
        ]
        placeholders = ",".join("?" for _ in cols)
        # Upsert keyed on id only: a UNIQUE(name) collision from a *different*
        # entity must surface as ConflictError, not silently replace the row.
        updates = ",".join(f"{c}=excluded.{c}" for c in cols if c != "id")
        try:
            self.db.execute(
                f"INSERT INTO {self.table} ({','.join(cols)}) "
                f"VALUES ({placeholders}) "
                f"ON CONFLICT(id) DO UPDATE SET {updates}",
                tuple(vals),
            )
        except Exception as e:  # sqlite3.IntegrityError (UNIQUE name, FK)
            if "UNIQUE" in str(e):
                raise ConflictError(kind=self.table, name=getattr(obj, "name", obj.id))
            raise
        return obj

    def save_many(self, objs: Iterable[E]) -> None:
        """Batch-upsert in ONE transaction — the path every high-volume
        writer shares (executor span batches, metric-sample flushes): a
        flush must not pay a transaction per row. No touch(): batch
        producers stamp their own timestamps."""
        objs = list(objs)
        if not objs:
            return
        cols = ["id", *self.columns, "data", "created_at", "updated_at"]
        updates = ",".join(f"{c}=excluded.{c}" for c in cols if c != "id")
        with self.db.tx() as conn:
            conn.executemany(
                f"INSERT INTO {self.table} ({','.join(cols)}) "
                f"VALUES ({','.join('?' for _ in cols)}) "
                f"ON CONFLICT(id) DO UPDATE SET {updates}",
                [
                    (
                        o.id,
                        *[self._column_value(o, c) for c in self.columns],
                        json.dumps(o.to_dict()), o.created_at, o.updated_at,
                    )
                    for o in objs
                ],
            )

    def get(self, id: str) -> E:
        rows = self.db.query(f"SELECT data FROM {self.table} WHERE id=?", (id,))
        if not rows:
            raise NotFoundError(kind=self.table, name=id)
        return self._hydrate(rows[0]["data"])

    def find(self, **where: object) -> list[E]:
        """Query by mirrored columns only."""
        clauses, params = [], []
        for k, v in where.items():
            if k not in self.columns and k != "id":
                raise ValueError(f"{self.table}: column {k} is not queryable")
            clauses.append(f"{k}=?")
            params.append(v)
        sql = f"SELECT data FROM {self.table}"
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        sql += " ORDER BY created_at"
        return [self._hydrate(r["data"]) for r in self.db.query(sql, tuple(params))]

    def get_by_name(self, name: str) -> E:
        if "name" not in self.columns:
            raise ValueError(f"{self.table} entities are not addressable by name")
        rows = self.db.query(f"SELECT data FROM {self.table} WHERE name=?", (name,))
        if not rows:
            raise NotFoundError(kind=self.table, name=name)
        return self._hydrate(rows[0]["data"])

    def list(self) -> list[E]:
        return self.find()

    def delete(self, id: str) -> None:
        self.get(id)  # NotFound if absent
        self.db.execute(f"DELETE FROM {self.table} WHERE id=?", (id,))

    def _column_value(self, obj: E, column: str) -> object:
        return getattr(obj, column)

    def _hydrate(self, blob: str) -> E:
        return self.entity.from_dict(json.loads(blob))


class CredentialRepo(EntityRepo[Credential]):
    table, entity, columns = "credentials", Credential, ("name",)


class RegionRepo(EntityRepo[Region]):
    table, entity, columns = "regions", Region, ("name", "provider")


class ZoneRepo(EntityRepo[Zone]):
    table, entity, columns = "zones", Zone, ("name", "region_id")


class PlanRepo(EntityRepo[Plan]):
    table, entity, columns = "plans", Plan, ("name", "provider", "accelerator")


class HostRepo(EntityRepo[Host]):
    table, entity, columns = "hosts", Host, ("name", "ip", "cluster_id", "status")


class ClusterRepo(EntityRepo[Cluster]):
    table, entity, columns = "clusters", Cluster, ("name", "project_id", "phase")

    def _column_value(self, obj: Cluster, column: str) -> object:
        if column == "phase":  # mirror the nested status.phase for queries
            return obj.status.phase
        return super()._column_value(obj, column)


class NodeRepo(EntityRepo[Node]):
    table, entity, columns = "nodes", Node, ("name", "cluster_id", "host_id", "role", "status")


class BackupAccountRepo(EntityRepo[BackupAccount]):
    table, entity, columns = "backup_accounts", BackupAccount, ("name",)


class BackupStrategyRepo(EntityRepo[BackupStrategy]):
    table, entity, columns = "backup_strategies", BackupStrategy, ("cluster_id",)


class BackupFileRepo(EntityRepo[BackupFile]):
    table, entity, columns = "backup_files", BackupFile, ("cluster_id", "name")


class ProjectRepo(EntityRepo[Project]):
    table, entity, columns = "projects", Project, ("name",)


class ProjectMemberRepo(EntityRepo[ProjectMember]):
    table, entity, columns = "project_members", ProjectMember, ("project_id", "user_id")


class UserRepo(EntityRepo[User]):
    table, entity, columns = "users", User, ("name",)


class AuditRepo(EntityRepo[AuditRecord]):
    table, entity, columns = "audit_log", AuditRecord, ("user_name",)

    _PRUNE_EVERY = 500
    _KEEP = 5000

    def record(self, rec: AuditRecord) -> None:
        """Append + amortized bound: every _PRUNE_EVERY writes the trail is
        trimmed back to the newest _KEEP rows, so the table stays bounded
        without a cron dependency."""
        self.save(rec)
        self._writes = getattr(self, "_writes", 0) + 1
        if self._writes % self._PRUNE_EVERY == 0:
            self.prune(self._KEEP)

    def tail(self, limit: int = 200) -> list[AuditRecord]:
        """Newest-first, capped IN SQL (an audit trail grows forever).
        rowid tiebreak: a burst of writes can share one time.time() stamp
        and the order must still be deterministic."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} "
            f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT ?",
            (max(1, min(limit, 1000)),),
        )
        return [self.entity.from_dict(json.loads(r[0])) for r in rows]

    def prune(self, keep: int = 5000) -> int:
        """Bounded trail: drop everything past the newest `keep` rows.
        Deletes by rowid (oldest-first with rowid tiebreak), never by a
        created_at cutoff — timestamp ties at the boundary must not take
        rows the bound promised to keep."""
        total = self.db.query(f"SELECT COUNT(*) FROM {self.table}")[0][0]
        excess = int(total) - keep
        if excess <= 0:
            return 0
        self.db.execute(
            f"DELETE FROM {self.table} WHERE {ROWID_SQL} IN ("
            f"SELECT {ROWID_SQL} FROM {self.table} "
            f"ORDER BY created_at ASC, {ROWID_SQL} ASC LIMIT ?)",
            (excess,),
        )
        return excess


class EventRepo(EntityRepo[Event]):
    """The durable event bus (migration 013 grew the 001 timeline table).
    sqlite's rowid is the stream cursor: insertion order == stream order,
    so `since()` is the one read the SSE feed, `koctl events --follow`
    and the chaos drills' story reconstruction all share."""

    table, entity, columns = (
        "events", Event, ("cluster_id", "kind", "op_id", "tenant"),
    )

    def since(self, after_rowid: int = 0, *, kind: str = "",
              cluster_id: str | None = None, tenant: str = "",
              limit: int = 500) -> tuple[list[tuple[int, Event]], int]:
        """Stream read: events past `after_rowid` in stream order, capped
        IN SQL, filtered on the mirrored columns. `kind` matches exactly
        or — with a trailing '.' — as a prefix ("queue." selects the
        whole queue stream). Returns ([(rowid, event), ...], new_cursor);
        the cursor is unchanged when nothing new landed, so a poll loop
        can hand it straight back."""
        clauses, params = [f"{ROWID_SQL} > ?"], [int(after_rowid)]
        if kind:
            if kind.endswith("."):
                clauses.append("kind LIKE ? ESCAPE '\\'")
                params.append(kind.replace("\\", "\\\\")
                              .replace("%", "\\%").replace("_", "\\_")
                              + "%")
            else:
                clauses.append("kind = ?")
                params.append(kind)
        if cluster_id is not None:
            clauses.append("cluster_id = ?")
            params.append(cluster_id)
        if tenant:
            clauses.append("tenant = ?")
            params.append(tenant)
        rows = self.db.query(
            f"SELECT {ROWID_SQL}, data FROM {self.table} "
            f"WHERE {' AND '.join(clauses)} ORDER BY {ROWID_SQL} LIMIT ?",
            (*params, max(1, min(int(limit), 5000))),
        )
        out = [(int(r["rowid"]), self._hydrate(r["data"])) for r in rows]
        return out, (out[-1][0] if out else int(after_rowid))

    def counts_by_kind(self) -> dict[str, int]:
        """Events by kind, computed IN SQL on the mirrored column — the
        `ko_tpu_events_total` family must not hydrate the bus per
        scrape. Legacy pre-bus rows group under ''."""
        rows = self.db.query(
            f"SELECT kind, COUNT(*) AS n FROM {self.table} GROUP BY kind")
        return {r["kind"]: int(r["n"]) for r in rows}

    def prune(self, keep: int) -> int:
        """Bounded bus: drop STREAM rows past the newest `keep`, by rowid
        (stream order), never a created_at cutoff — timestamp ties at the
        boundary must not take rows the bound promised to keep. TIMELINE
        rows are exempt: chatty op.*/queue.* traffic must never evict an
        older cluster's human history (create/backup/escalation trail),
        which was retained forever before the bus existed. Cursor
        semantics survive pruning: rowids only grow, so a resumed
        `Last-Event-ID` past the pruned range replays nothing stale."""
        if keep < 1:
            return 0
        with self.db.tx() as conn:
            cur = conn.execute(
                f"DELETE FROM {self.table} "
                f"WHERE NOT {self.TIMELINE_WHERE} AND {ROWID_SQL} NOT IN ("
                f"SELECT {ROWID_SQL} FROM {self.table} "
                f"WHERE NOT {self.TIMELINE_WHERE} "
                f"ORDER BY {ROWID_SQL} DESC LIMIT ?)",
                (int(keep),),
            )
            return max(cur.rowcount, 0)

    # the legacy TIMELINE subset of the bus: human-raised cluster rows
    # (pre-bus rows, the cluster.event stream, watchdog escalations) —
    # the UI feed and `koctl cluster events` keep their pre-bus signal
    # instead of drowning in per-phase op.* rows, which stay reachable
    # through the stream surface (`since`, kind filters)
    TIMELINE_WHERE = ("(kind IN ('', 'cluster.event') "
                      "OR kind LIKE 'watchdog.%')")

    def timeline(self, cluster_id: str) -> list[Event]:
        """One cluster's human timeline rows, oldest first (the
        EventService.list contract, pre-bus shape)."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE cluster_id=? "
            f"AND {self.TIMELINE_WHERE} ORDER BY created_at, {ROWID_SQL}",
            (cluster_id,),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def find_recent(self, cluster_ids: Iterable[str],
                    limit: int) -> list[Event]:
        """Newest-first TIMELINE feed across clusters, capped IN SQL —
        the activity endpoint must not hydrate every event ever emitted
        just to keep the newest few hundred."""
        ids = list(cluster_ids)
        if not ids or limit < 1:
            return []
        placeholders = ",".join("?" for _ in ids)
        rows = self.db.query(
            f"SELECT data FROM {self.table} "
            f"WHERE cluster_id IN ({placeholders}) "
            f"AND {self.TIMELINE_WHERE} "
            f"ORDER BY created_at DESC LIMIT ?",
            (*ids, limit),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def count_for(self, cluster_ids: Iterable[str]) -> int:
        ids = list(cluster_ids)
        if not ids:
            return 0
        placeholders = ",".join("?" for _ in ids)
        rows = self.db.query(
            f"SELECT COUNT(*) AS n FROM {self.table} "
            f"WHERE cluster_id IN ({placeholders}) "
            f"AND {self.TIMELINE_WHERE}",
            tuple(ids),
        )
        return int(rows[0]["n"])


class MessageRepo(EntityRepo[Message]):
    table, entity, columns = "messages", Message, ("user_id",)


class TaskLogChunkRepo(EntityRepo[TaskLogChunk]):
    table, entity, columns = "task_log_chunks", TaskLogChunk, ("cluster_id", "task_id", "seq")

    def append(self, cluster_id: str, task_id: str, lines: Iterable[str]) -> None:
        """Batch-insert a chunk of lines in ONE transaction; the seq base is
        read inside the same tx so concurrent appenders can't collide."""
        lines = list(lines)
        if not lines:
            return
        with self.db.tx() as conn:
            row = conn.execute(
                "SELECT COALESCE(MAX(seq),-1)+1 AS n FROM task_log_chunks "
                "WHERE task_id=?",
                (task_id,),
            ).fetchone()
            start = int(row["n"])
            chunks = [
                TaskLogChunk(
                    cluster_id=cluster_id, task_id=task_id, seq=start + i, line=line
                )
                for i, line in enumerate(lines)
            ]
            conn.executemany(
                "INSERT INTO task_log_chunks "
                "(id, cluster_id, task_id, seq, data, created_at, updated_at) "
                "VALUES (?,?,?,?,?,?,?)",
                [
                    (
                        c.id, c.cluster_id, c.task_id, c.seq,
                        json.dumps(c.to_dict()), c.created_at, c.updated_at,
                    )
                    for c in chunks
                ],
            )

    def tail(self, task_id: str, after_seq: int = -1) -> list[TaskLogChunk]:
        rows = self.db.query(
            "SELECT data FROM task_log_chunks WHERE task_id=? AND seq>? ORDER BY seq",
            (task_id, after_seq),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def tail_cluster(
        self, cluster_id: str, after_rowid: int = 0
    ) -> tuple[list[TaskLogChunk], int]:
        """Cluster-wide stream cursor on sqlite rowid: O(new rows) per poll
        (insertion order == stream order). Returns (chunks, last_rowid)."""
        rows = self.db.query(
            f"SELECT {ROWID_SQL}, data FROM task_log_chunks "
            f"WHERE cluster_id=? AND {ROWID_SQL}>? ORDER BY {ROWID_SQL}",
            (cluster_id, after_rowid),
        )
        chunks = [self._hydrate(r["data"]) for r in rows]
        last = rows[-1]["rowid"] if rows else after_rowid
        return chunks, last


class ComponentRepo(EntityRepo[ClusterComponent]):
    table, entity, columns = "components", ClusterComponent, ("cluster_id", "name")


class OperationRepo(EntityRepo[Operation]):
    """Operation journal rows (models/operation.py). `status` is mirrored so
    the boot reconciler's open-op sweep is one indexed query, not a
    hydrate-everything scan; `summary` (migration 012) mirrors the compact
    vars digest engines maintain, so history listings and latest-op
    resolution never hydrate historical vars blobs."""

    table, entity, columns = "operations", Operation, (
        "cluster_id", "kind", "status", "parent_op_id", "summary",
    )

    def _column_value(self, obj: Operation, column: str) -> object:
        if column == "summary":   # dict → TEXT mirror ('' = no digest)
            return json.dumps(obj.summary) if obj.summary else ""
        return super()._column_value(obj, column)

    def children(self, parent_op_id: str) -> list[Operation]:
        """A fleet op's per-cluster child ops, in launch order (the
        indexed parent link from migration 007)."""
        return self.find(parent_op_id=parent_op_id)

    def latest(self, kinds) -> Operation | None:
        """Newest op of the given kind(s) — ONE indexed probe (the
        (kind, created_at) index from migration 012) hydrating ONE row,
        however long the journal history is. The id tiebreak matches the
        (created_at, id) sort resolve_op_ref's slow path used."""
        kinds = (kinds,) if isinstance(kinds, str) else tuple(kinds)
        marks = ",".join("?" for _ in kinds)
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE kind IN ({marks}) "
            f"ORDER BY created_at DESC, id DESC LIMIT 1", kinds)
        return self._hydrate(rows[0]["data"]) if rows else None

    def find_id_prefix(self, kinds, prefix: str) -> list[Operation]:
        """Ops of the given kind(s) whose id starts with `prefix`, IN SQL
        — prefix resolution must not hydrate the whole history to match
        one row. LIKE special characters are escaped (op ids are hex, but
        the ref comes from the operator)."""
        kinds = (kinds,) if isinstance(kinds, str) else tuple(kinds)
        marks = ",".join("?" for _ in kinds)
        escaped = (prefix.replace("\\", "\\\\").replace("%", "\\%")
                   .replace("_", "\\_"))
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE kind IN ({marks}) "
            f"AND id LIKE ? ESCAPE '\\' ORDER BY created_at, id",
            (*kinds, escaped + "%"))
        return [self._hydrate(r["data"]) for r in rows]

    def summaries(self, kind: str, limit: int = 1000) -> list[dict]:
        """Newest-first history digests straight off the mirrored
        columns — id/status/summary/timestamps, NO vars hydration. The
        constant-cost backing of `fleet status`'s list form; rows whose
        engine predates the summary column carry an empty digest."""
        rows = self.db.query(
            f"SELECT id, status, summary, created_at, updated_at "
            f"FROM {self.table} WHERE kind=? "
            f"ORDER BY created_at DESC, id DESC LIMIT ?",
            (kind, max(1, min(limit, 10000))))
        out: list[dict] = []
        for r in rows:
            digest = json.loads(r["summary"]) if r["summary"] else {}
            out.append({"id": r["id"], "status": r["status"],
                        "summary": digest,
                        "created_at": float(r["created_at"]),
                        "updated_at": float(r["updated_at"])})
        return out

    def history(self, cluster_id: str, limit: int = 50) -> list[Operation]:
        """Newest-first journal history, capped IN SQL (the journal grows
        with every operation forever; rowid tiebreak keeps bursts stable)."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE cluster_id=? "
            f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT ?",
            (cluster_id, max(1, min(limit, 1000))),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def count_by_status(self) -> dict[str, int]:
        """Journal rows by status, computed IN SQL on the mirrored column —
        the /metrics journal gauge must not hydrate the whole history per
        scrape."""
        rows = self.db.query(
            f"SELECT status, COUNT(*) AS n FROM {self.table} "
            f"GROUP BY status"
        )
        return {r["status"]: int(r["n"]) for r in rows}


# platform-scope (cluster_id == '') op kinds with a RESUME path: their
# Interrupted rows are parked work whose span trees `journal.reopen`
# re-arms, so the span prune must not collect them. Mirrors the
# service-layer contract (fleet/engine.py FLEET_UPGRADE_KIND +
# reconcile.py AUTO_RESUME_FLEET/AUTO_RESUME_QUEUE; queue entry ops are
# re-armed by WorkloadQueueService.recover) — the repository layer cannot
# import either without inverting the layering, and tests pin the
# agreement.
RESUMABLE_SCOPED_KINDS = ("fleet-upgrade", "workload-queued")


class SpanRepo(EntityRepo[Span]):
    """Operation trace spans (models/span.py). Timing fields are mirrored
    into real columns so the scrape-time histogram collectors and the trace
    endpoint run on indexed SQL, never a hydrate-everything scan."""

    table, entity, columns = "spans", Span, (
        "trace_id", "parent_id", "op_id", "cluster_id", "kind", "name",
        "status", "started_at", "finished_at",
    )

    def for_operation(self, op_id: str) -> list[Span]:
        """Every span of one operation, start-ordered (rowid tiebreak keeps
        same-timestamp siblings stable)."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE op_id=? "
            f"ORDER BY started_at, {ROWID_SQL}",
            (op_id,),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def for_trace(self, trace_id: str) -> list[Span]:
        """Every span of one TRACE, across operations — a fleet rollout's
        child ops share the fleet op's trace id, so this is how the whole
        fleet → wave → cluster → phase waterfall comes back as ONE tree."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE trace_id=? "
            f"ORDER BY started_at, {ROWID_SQL}",
            (trace_id,),
        )
        return [self._hydrate(r["data"]) for r in rows]

    def duration_rows(self, kind: str) -> list[tuple]:
        """(name, duration_s, trace_id) for every FINISHED span of `kind` —
        the histogram collectors' raw material, straight off the mirrored
        columns (no JSON hydration on the scrape path)."""
        rows = self.db.query(
            f"SELECT name, finished_at - started_at AS d, trace_id "
            f"FROM {self.table} "
            f"WHERE kind=? AND started_at > 0 AND finished_at > 0 "
            f"ORDER BY {ROWID_SQL}",
            (kind,),
        )
        return [(r["name"], float(r["d"]), r["trace_id"]) for r in rows]

    def prune_to_operations(self, keep: int) -> int:
        """Bounded trace store: keep spans of the newest `keep` operations
        (by the operations table's own ordering) and drop the rest — the
        span tree of a two-month-old create is journal history, not a
        debugging artifact worth its disk.

        Live ops are NEVER pruned, however old: a fleet rollout over more
        clusters than `keep` closes a child op (→ this prune) hundreds of
        times while its own root/wave spans and earliest child subtrees
        are the oldest rows in the store — and a resumable op's spans are
        what `journal.reopen` re-arms. Open/parked/interrupted ops and
        the children stitched under them are one retention unit.

        The Interrupted exemption covers RESUMABLE kinds only (today:
        fleet rollouts — `journal.reopen` re-arms their spans, so
        pruning a parked rollout would lose the tree resume needs). A
        per-cluster op swept to Interrupted at boot is superseded by a
        fresh op on retry, and a platform-scope WORKLOAD op never
        resumes at all (re-running the workload is the recovery) —
        exempting either would let a crash loop grow the span store
        without bound."""
        if keep < 1:
            return 0

        def live(alias: str) -> str:
            kinds = ", ".join(f"'{k}'" for k in RESUMABLE_SCOPED_KINDS)
            return (f"{alias}status IN ('Running', 'Paused') "
                    f"OR ({alias}status = 'Interrupted' "
                    f"AND {alias}cluster_id = '' "
                    f"AND {alias}kind IN ({kinds}))")

        # cursor rowcount, NOT before/after COUNT(*) scans: this runs on
        # every operation close, on the operation's worker thread
        with self.db.tx() as conn:
            cur = conn.execute(
                f"DELETE FROM {self.table} WHERE op_id NOT IN ("
                f"SELECT id FROM operations "
                f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT ?) "
                f"AND op_id NOT IN ("
                f"SELECT id FROM operations WHERE {live('')}) "
                f"AND op_id NOT IN ("
                f"SELECT o.id FROM operations o JOIN operations p "
                f"ON o.parent_op_id = p.id WHERE {live('p.')})",
                (keep,),
            )
            return max(cur.rowcount, 0)


class MetricSampleRepo(EntityRepo[MetricSample]):
    """Per-step training telemetry rows (migration 013). loss/step_s are
    mirrored into real columns so the scrape-time histogram collectors
    and the live metrics endpoint run on indexed SQL; sqlite rowid is the
    follow-stream cursor, exactly like the event bus."""

    table, entity, columns = (
        "metric_samples", MetricSample,
        ("op_id", "step", "kind", "tenant", "loss", "step_s"),
    )

    def since(self, op_id: str, after_rowid: int = 0,
              limit: int = 2000) -> tuple[list[tuple[int, MetricSample]], int]:
        """Follow-stream read for one op: samples past `after_rowid` in
        stream order. Returns ([(rowid, sample), ...], new_cursor)."""
        rows = self.db.query(
            f"SELECT {ROWID_SQL}, data FROM {self.table} "
            f"WHERE op_id = ? AND {ROWID_SQL} > ? ORDER BY {ROWID_SQL} LIMIT ?",
            (op_id, int(after_rowid), max(1, min(int(limit), 10000))),
        )
        out = [(int(r["rowid"]), self._hydrate(r["data"])) for r in rows]
        return out, (out[-1][0] if out else int(after_rowid))

    def step_rows(self) -> list[tuple]:
        """(tenant, step_s) for every step sample — the
        `ko_tpu_workload_step_seconds` histogram's raw material, straight
        off the mirrored columns (no JSON hydration on the scrape
        path)."""
        rows = self.db.query(
            f"SELECT tenant, step_s FROM {self.table} "
            f"WHERE kind = 'step' AND step_s > 0 ORDER BY {ROWID_SQL}")
        return [(r["tenant"], float(r["step_s"])) for r in rows]

    def request_rows(self) -> list[tuple]:
        """(tenant, request_latency_s) for every serving request sample —
        the `ko_tpu_workload_request_seconds` histogram's raw material
        (docs/workloads.md "Serving"); idx_metric_samples_kind serves the
        kind + step_s predicate pair exactly as it does for steps."""
        rows = self.db.query(
            f"SELECT tenant, step_s FROM {self.table} "
            f"WHERE kind = 'request' AND step_s > 0 ORDER BY {ROWID_SQL}")
        return [(r["tenant"], float(r["step_s"])) for r in rows]

    def latest_losses(self) -> list[tuple]:
        """(op_id, tenant, step, loss) of each op's NEWEST step sample —
        the `ko_tpu_workload_loss` gauge's raw material, one indexed
        group-by (cardinality bounded by op retention: samples prune
        with their op's spans)."""
        rows = self.db.query(
            f"SELECT op_id, tenant, step, loss, MAX({ROWID_SQL}) "
            f"FROM {self.table} WHERE kind = 'step' GROUP BY op_id")
        return [(r["op_id"], r["tenant"], int(r["step"]), float(r["loss"]))
                for r in rows]

    def prune_ring(self, op_id: str, keep: int) -> int:
        """The per-op ring bound: keep the NEWEST `keep` rows of one op
        (a long train's live tail matters; its hour-old samples do not).
        Called from the tracer's flush path, so it must be one cheap
        indexed DELETE."""
        if keep < 1:
            return 0
        with self.db.tx() as conn:
            cur = conn.execute(
                f"DELETE FROM {self.table} WHERE op_id = ? "
                f"AND {ROWID_SQL} NOT IN ("
                f"SELECT {ROWID_SQL} FROM {self.table} WHERE op_id = ? "
                f"ORDER BY {ROWID_SQL} DESC LIMIT ?)",
                (op_id, op_id, int(keep)),
            )
            return max(cur.rowcount, 0)

    def prune_to_operations(self, keep: int) -> int:
        """Retention twin of SpanRepo.prune_to_operations: samples of
        operations older than the newest `keep` are history, not live
        telemetry. Runs on the same close path; Running/Paused ops are
        never pruned (their watch streams are live)."""
        if keep < 1:
            return 0
        with self.db.tx() as conn:
            cur = conn.execute(
                f"DELETE FROM {self.table} WHERE op_id NOT IN ("
                f"SELECT id FROM operations "
                f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT ?) "
                f"AND op_id NOT IN ("
                f"SELECT id FROM operations "
                f"WHERE status IN ('Running', 'Paused'))",
                (int(keep),),
            )
            return max(cur.rowcount, 0)


class CisScanRepo(EntityRepo[CisScan]):
    table, entity, columns = "cis_scans", CisScan, ("cluster_id", "status")


class SettingRepo(EntityRepo[Setting]):
    table, entity, columns = "settings", Setting, ("name",)


class CheckpointRepo(EntityRepo[Checkpoint]):
    """Training-checkpoint index rows (migration 010). Only COMPLETE
    checkpoints are restorable; latest_complete() is the one query the
    resume paths (workload --resume, the slice pool's degrade leg, the
    reconciler's orphan sweep) share, so "latest" can never mean
    different rows to different layers."""

    table, entity, columns = (
        "checkpoints", Checkpoint, ("op_id", "tenant", "step", "status"),
    )

    def latest_complete(self, op_id: str = "",
                        tenant: str | None = None) -> Checkpoint | None:
        """Newest complete checkpoint — of one op when `op_id` is given,
        of one TENANT namespace when `tenant` is given (None = any;
        "" = the unnamed namespace), across everything otherwise.
        Save-order by (created_at, rowid) so two checkpoints inside one
        clock tick stay ordered."""
        clauses, params = ["status = 'complete'"], []
        if op_id:
            clauses.append("op_id = ?")
            params.append(op_id)
        if tenant is not None:
            clauses.append("tenant = ?")
            params.append(tenant)
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE {' AND '.join(clauses)} "
            f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT 1",
            tuple(params),
        )
        return self._hydrate(rows[0]["data"]) if rows else None

    def complete(self, tenant: str | None = None) -> list[Checkpoint]:
        """All complete checkpoints, OLDEST first (the retention pruner
        walks this from the front). `tenant` scopes to one namespace —
        retention is per tenant, so one tenant's churn can never prune
        another's rows."""
        clauses, params = ["status = 'complete'"], []
        if tenant is not None:
            clauses.append("tenant = ?")
            params.append(tenant)
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE {' AND '.join(clauses)} "
            f"ORDER BY created_at, {ROWID_SQL}", tuple(params))
        return [self._hydrate(r["data"]) for r in rows]


class WorkloadQueueRepo(EntityRepo[QueueEntry]):
    """Workload-queue entries (migration 011) — the scheduler's queryable
    mirror of the entry journal ops. The scheduler's pending pick and the
    metrics families run on mirrored columns; the entry's full state
    (placement, preemption ledger, run ops) rides the JSON document and
    the op's vars."""

    table, entity, columns = (
        "workload_queue", QueueEntry,
        ("op_id", "tenant", "priority_class", "priority", "state",
         "started_at", "kind"),
    )

    def pending(self) -> list[QueueEntry]:
        """Schedulable entries in dispatch order: highest priority class
        first, FIFO within a class (rowid tiebreak for same-tick
        bursts)."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE state = 'pending' "
            f"ORDER BY priority DESC, created_at ASC, {ROWID_SQL} ASC")
        return [self._hydrate(r["data"]) for r in rows]

    def active(self) -> list[QueueEntry]:
        """Entries holding capacity (placed/running), oldest first."""
        rows = self.db.query(
            f"SELECT data FROM {self.table} "
            f"WHERE state IN ('placed', 'running') "
            f"ORDER BY created_at ASC, {ROWID_SQL} ASC")
        return [self._hydrate(r["data"]) for r in rows]

    def by_op(self, op_id: str) -> QueueEntry | None:
        rows = self.find(op_id=op_id)
        return rows[0] if rows else None

    def counts_by_state(self) -> dict[str, int]:
        """Entries by state, computed IN SQL on the mirrored column — the
        `ko_tpu_workload_queue` gauge must not hydrate queue history per
        scrape."""
        rows = self.db.query(
            f"SELECT state, COUNT(*) AS n FROM {self.table} "
            f"GROUP BY state")
        return {r["state"]: int(r["n"]) for r in rows}

    def wait_rows(self) -> list[tuple]:
        """(priority_class, queue_wait_seconds) for every entry that was
        dispatched at least once — the queue-wait histogram's raw
        material, straight off the mirrored columns."""
        rows = self.db.query(
            f"SELECT priority_class, started_at - created_at AS w "
            f"FROM {self.table} WHERE started_at > 0 ORDER BY {ROWID_SQL}")
        return [(r["priority_class"], max(float(r["w"]), 0.0))
                for r in rows]

    def running_counts(self) -> dict[tuple, int]:
        """(priority_class, kind) → live running-entry count, computed
        IN SQL on the mirrored columns (idx_workload_queue_state leads
        with state) — the gauge's per-priority `running` dimension must
        not hydrate the queue per scrape."""
        rows = self.db.query(
            f"SELECT priority_class, kind, COUNT(*) AS n "
            f"FROM {self.table} WHERE state = 'running' "
            f"GROUP BY priority_class, kind")
        return {(r["priority_class"], r["kind"]): int(r["n"])
                for r in rows}


class SliceEventRepo(EntityRepo[SliceEvent]):
    """Per-slice incident ledger rows (migration 009) — find() by
    cluster/slice/kind/op rides the mirrored columns; rows are
    append-only in practice (the pool never rewrites history)."""

    table, entity, columns = (
        "slice_events", SliceEvent,
        ("cluster_id", "slice_id", "kind", "op_id"),
    )

    def for_cluster(self, cluster_id: str, limit: int = 100) -> list[SliceEvent]:
        rows = self.db.query(
            f"SELECT data FROM {self.table} WHERE cluster_id=? "
            f"ORDER BY created_at DESC, {ROWID_SQL} DESC LIMIT ?",
            (cluster_id, int(limit)),
        )
        return [self._hydrate(r["data"]) for r in rows]


# DB_NOW_SQL / ROWID_SQL (imported above, re-exported here for the lease
# and stream consumers that always lived off this module) are the two
# sanctioned dialect seams — db.py holds the definitions and
# docs/resilience.md "SQL contract" names their Postgres translations.

# lease resources currently backed by a Running operation (a cluster id,
# or the op's own id for fleet-scope ops) — the ONE definition shared by
# the heartbeat's re-arm rule and the release guard below, so the two can
# never disagree about what counts as live work
RUNNING_RESOURCES_SQL = (
    "(SELECT cluster_id FROM operations WHERE status = 'Running' "
    " UNION "
    " SELECT id FROM operations WHERE status = 'Running')"
)


class LeaseRepo:
    """Controller leases (migration 008) — NOT an EntityRepo: lease rows
    are plain columns mutated by single-statement compare-and-swaps, so
    two replicas racing on one file resolve inside SQLite itself, with no
    read-modify-write window for them to interleave in.

    `epoch` is the fencing token: monotonic per resource, bumped only when
    ownership CHANGES HANDS (a same-controller re-claim is a renewal). The
    journal stamps every operation with the epoch it was claimed under and
    rejects writes whose epoch is no longer current (resilience/lease.py).
    Rows are never deleted — release just zeroes the deadline — which is
    what keeps epochs monotonic across successive owners."""

    table = "controller_leases"

    def __init__(self, db: Database) -> None:
        self.db = db

    def db_now(self) -> float:
        """The db clock (epoch seconds) — the ONE time source leases use."""
        return float(self.db.query(f"SELECT {DB_NOW_SQL} AS t")[0]["t"])

    def claim(self, resource: str, controller_id: str,
              ttl_s: float) -> dict | None:
        """One CAS: win if the lease is free (no row), expired, or already
        ours (a renewal — epoch unchanged). A takeover from ANOTHER
        controller bumps the epoch, fencing the previous holder's writes.
        Returns the lease row on a win, None if a live foreign holder kept
        it."""
        with self.db.tx() as conn:
            cur = conn.execute(
                f"INSERT INTO {self.table} "
                f"(resource, controller_id, epoch, heartbeat_deadline, "
                f" renewed_at) "
                f"VALUES (?, ?, 1, {DB_NOW_SQL} + ?, {DB_NOW_SQL}) "
                f"ON CONFLICT(resource) DO UPDATE SET "
                # RHS reads the PRE-update row, so the epoch bump sees the
                # old controller_id whatever the SET order
                f"  epoch = {self.table}.epoch + "
                f"    ({self.table}.controller_id != excluded.controller_id), "
                f"  controller_id = excluded.controller_id, "
                f"  heartbeat_deadline = excluded.heartbeat_deadline, "
                f"  renewed_at = excluded.renewed_at "
                f"WHERE {self.table}.controller_id = excluded.controller_id "
                f"   OR {self.table}.heartbeat_deadline < {DB_NOW_SQL}",
                (resource, controller_id, ttl_s),
            )
            if cur.rowcount < 1:
                return None   # a live foreign holder won the CAS
            row = conn.execute(
                f"SELECT * FROM {self.table} WHERE resource=?",
                (resource,),
            ).fetchone()
        return dict(row)

    def renew(self, controller_id: str, ttl_s: float) -> int:
        """Heartbeat: extend every lease this controller holds, in one
        statement however many clusters it owns. Live leases renew
        unconditionally; an EXPIRED lease re-arms only while a Running
        operation still backs it — a stalled heartbeat (long cron tick, GC
        pause) must not forfeit a healthy in-flight op to a peer's sweep,
        but idle expired leases stay down (a revived replica's heartbeat
        must never resurrect stale ownership of clusters nothing is
        running on, which would refuse peers' future claims). The WHERE on
        controller_id makes this CAS-safe: if a peer's sweep already took
        the resource over, the row's controller changed and this statement
        cannot touch it. Released leases (deadline zeroed) are excluded by
        the deadline > 0 guard."""
        with self.db.tx() as conn:
            cur = conn.execute(
                f"UPDATE {self.table} SET "
                f"  heartbeat_deadline = {DB_NOW_SQL} + ?, "
                f"  renewed_at = {DB_NOW_SQL} "
                f"WHERE controller_id = ? "
                f"  AND (heartbeat_deadline >= {DB_NOW_SQL} "
                f"       OR (heartbeat_deadline > 0 "
                f"           AND resource IN {RUNNING_RESOURCES_SQL}))",
                (ttl_s, controller_id),
            )
            return max(cur.rowcount, 0)

    def release(self, resource: str, controller_id: str, epoch: int) -> bool:
        """Expire our own lease at operation close. CAS on (controller,
        epoch): a successor's lease is never touched by a late release
        from the replica it fenced out. The NOT-IN guard keeps a release
        from zeroing a lease a RUNNING operation rides: the reconciler's
        settle-release races its own auto-resume engines' re-opens (a
        resumed fleet rollout re-claims its wave clusters asynchronously),
        and a same-controller re-claim keeps the epoch, so the (controller,
        epoch) CAS alone cannot tell 'my stale sweep claim' from 'my
        engine's live re-claim' — the journal can: open() commits its
        claim and its Running row in one transaction."""
        with self.db.tx() as conn:
            cur = conn.execute(
                f"UPDATE {self.table} SET heartbeat_deadline = 0 "
                f"WHERE resource=? AND controller_id=? AND epoch=? "
                f"  AND resource NOT IN {RUNNING_RESOURCES_SQL}",
                (resource, controller_id, epoch),
            )
            return cur.rowcount > 0

    def get(self, resource: str) -> dict | None:
        rows = self.db.query(
            f"SELECT *, heartbeat_deadline >= {DB_NOW_SQL} AS live "
            f"FROM {self.table} WHERE resource=?",
            (resource,),
        )
        return dict(rows[0]) if rows else None

    def current_epoch(self, resource: str) -> int:
        """The fencing check's read: the resource's current epoch (0 when
        no lease row exists — nothing to fence against)."""
        rows = self.db.query(
            f"SELECT epoch FROM {self.table} WHERE resource=?", (resource,))
        return int(rows[0]["epoch"]) if rows else 0

    def expired(self) -> list[dict]:
        """Every lease past its deadline (released rows included — the
        sweep filters by whether open operations exist behind them)."""
        return [dict(r) for r in self.db.query(
            f"SELECT * FROM {self.table} "
            f"WHERE heartbeat_deadline < {DB_NOW_SQL} ORDER BY resource")]

    def state_counts(self, controller_id: str) -> dict[str, int]:
        """{held, foreign, expired} from this controller's viewpoint — the
        /metrics gauge's raw material, one indexed pass in SQL."""
        rows = self.db.query(
            f"SELECT CASE "
            f"  WHEN heartbeat_deadline < {DB_NOW_SQL} THEN 'expired' "
            f"  WHEN controller_id = ? THEN 'held' "
            f"  ELSE 'foreign' END AS state, COUNT(*) AS n "
            f"FROM {self.table} GROUP BY state",
            (controller_id,),
        )
        counts = {"held": 0, "foreign": 0, "expired": 0}
        for r in rows:
            counts[r["state"]] = int(r["n"])
        return counts

    def max_heartbeat_age_s(self, controller_id: str) -> float | None:
        """Oldest heartbeat age (db-now − renewed_at) across the leases
        this controller still holds live; None when it holds none."""
        rows = self.db.query(
            f"SELECT MAX({DB_NOW_SQL} - renewed_at) AS age FROM {self.table} "
            f"WHERE controller_id = ? AND heartbeat_deadline >= {DB_NOW_SQL}",
            (controller_id,),
        )
        age = rows[0]["age"] if rows else None
        return float(age) if age is not None else None


class Repositories:
    """One bundle handed to every service (the reference injects repos into
    services the same way, SURVEY.md §2.1 row 1b)."""

    def __init__(self, db: Database) -> None:
        self.db = db
        self.credentials = CredentialRepo(db)
        self.regions = RegionRepo(db)
        self.zones = ZoneRepo(db)
        self.plans = PlanRepo(db)
        self.hosts = HostRepo(db)
        self.clusters = ClusterRepo(db)
        self.nodes = NodeRepo(db)
        self.backup_accounts = BackupAccountRepo(db)
        self.backup_strategies = BackupStrategyRepo(db)
        self.backup_files = BackupFileRepo(db)
        self.projects = ProjectRepo(db)
        self.project_members = ProjectMemberRepo(db)
        self.users = UserRepo(db)
        self.events = EventRepo(db)
        self.messages = MessageRepo(db)
        self.task_logs = TaskLogChunkRepo(db)
        self.components = ComponentRepo(db)
        self.operations = OperationRepo(db)
        self.spans = SpanRepo(db)
        self.metric_samples = MetricSampleRepo(db)
        self.cis_scans = CisScanRepo(db)
        self.settings = SettingRepo(db)
        self.slice_events = SliceEventRepo(db)
        self.checkpoints = CheckpointRepo(db)
        self.workload_queue = WorkloadQueueRepo(db)
        self.audit = AuditRepo(db)
        self.leases = LeaseRepo(db)
