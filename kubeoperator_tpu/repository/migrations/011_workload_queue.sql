-- Workload queue (docs/workloads.md "Queue and preemption"): one row per
-- queued tenant workload — the queryable mirror of the entry's journal
-- op. The scheduler's pending pick ("highest priority, FIFO within
-- class") and the /metrics state gauge run on the mirrored columns;
-- started_at is mirrored so the queue-wait histogram is one SQL pass
-- (started_at - created_at), no JSON hydration on the scrape path.
CREATE TABLE IF NOT EXISTS workload_queue (
    id TEXT PRIMARY KEY,
    op_id TEXT NOT NULL,
    tenant TEXT NOT NULL,
    priority_class TEXT NOT NULL,
    priority INTEGER NOT NULL,
    state TEXT NOT NULL,
    started_at REAL NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_workload_queue_state
    ON workload_queue (state, priority, created_at);
CREATE INDEX IF NOT EXISTS idx_workload_queue_op ON workload_queue (op_id);
-- Per-tenant checkpoint namespaces (ISSUE 12 satellite): mirror the
-- owning tenant onto the checkpoint index so per-tenant retention and
-- `workload checkpoints --tenant` filter in SQL. Existing rows predate
-- tenancy and read as the unnamed namespace ('').
ALTER TABLE checkpoints ADD COLUMN tenant TEXT NOT NULL DEFAULT '';
CREATE INDEX IF NOT EXISTS idx_checkpoints_tenant
    ON checkpoints (tenant, status, created_at);
