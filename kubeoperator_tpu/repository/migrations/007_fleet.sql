-- Fleet operations (docs/resilience.md "Fleet operations"): a fleet op is
-- itself a journal row (005_operations.sql) with an empty cluster_id; its
-- per-cluster child operations (upgrade / rollback) link back through
-- parent_op_id, so "which clusters did this rollout touch" is one indexed
-- query and the boot reconciler can sweep an interrupted rollout together
-- with its stranded child op.
ALTER TABLE operations ADD COLUMN parent_op_id TEXT NOT NULL DEFAULT '';
CREATE INDEX IF NOT EXISTS idx_operations_parent ON operations (parent_op_id);
