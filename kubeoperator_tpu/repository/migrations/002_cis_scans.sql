-- CIS security scans (kube-bench runs) — SURVEY.md §1 Day-2 operations.
CREATE TABLE IF NOT EXISTS cis_scans (
    id TEXT PRIMARY KEY,
    cluster_id TEXT NOT NULL,
    status TEXT NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_cis_scans_cluster ON cis_scans (cluster_id);
