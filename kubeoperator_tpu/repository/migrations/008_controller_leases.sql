-- Controller leases: fenced cluster ownership for the multi-controller
-- control plane (resilience/lease.py, docs/resilience.md "Controller
-- leases"). One row per leased resource (a cluster id, or a fleet op id
-- for fleet-scope operations). `epoch` is the fencing token: it is
-- monotonic per resource — bumped ONLY when ownership changes hands — and
-- every journal/status write carries the epoch it was claimed under, so a
-- controller that lost its lease mid-operation (GC pause, partition,
-- zombie thread after a crash) can never corrupt the successor's journal.
-- Rows are never deleted (release just expires the deadline), which is
-- what keeps the epoch monotonic across owners.
CREATE TABLE controller_leases (
    resource            TEXT PRIMARY KEY,
    controller_id       TEXT NOT NULL,
    epoch               INTEGER NOT NULL,
    -- both stamped from the DATABASE clock (julianday('now')), never a
    -- replica's local clock: expiry must mean the same instant to every
    -- replica sharing the file
    heartbeat_deadline  REAL NOT NULL,
    renewed_at          REAL NOT NULL
);
CREATE INDEX idx_leases_controller ON controller_leases(controller_id);
CREATE INDEX idx_leases_deadline ON controller_leases(heartbeat_deadline);
