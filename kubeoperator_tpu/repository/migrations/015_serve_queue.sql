-- Concurrent gang dispatch + the serve verb (docs/workloads.md
-- "Serving"): mirror the entry kind so kind-aware scheduler queries and
-- the per-priority running gauge stay in SQL. Existing rows predate the
-- column and read as 'train' — exactly what they all were.
ALTER TABLE workload_queue ADD COLUMN kind TEXT NOT NULL DEFAULT 'train';
