-- Training-checkpoint index (docs/workloads.md "Checkpoints"): one row
-- per COMPLETE on-disk checkpoint (the manifest-last file contract is
-- the source of truth for completeness; this table is the queryable
-- mirror the resume/failover paths use to find "the latest complete
-- checkpoint" without scanning directories). op_id joins back to the
-- workload operation that saved it; rows outlive their directories
-- (status flips to pruned/swept) as an audit trail.
CREATE TABLE IF NOT EXISTS checkpoints (
    id TEXT PRIMARY KEY,
    op_id TEXT NOT NULL,
    step INTEGER NOT NULL,
    status TEXT NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_checkpoints_op ON checkpoints (op_id);
CREATE INDEX IF NOT EXISTS idx_checkpoints_status
    ON checkpoints (status, created_at);
