-- Operation trace spans (docs/observability.md): one row per node of the
-- operation -> phase -> attempt -> task -> host tree, keyed by the owning
-- journal operation (005_operations.sql). Written live as spans start and
-- finish, so a controller killed mid-operation leaves the spans recorded
-- so far (status Running) as evidence of where the wall-clock stopped.
CREATE TABLE IF NOT EXISTS spans (
    id TEXT PRIMARY KEY,
    trace_id TEXT NOT NULL,
    parent_id TEXT NOT NULL,
    op_id TEXT NOT NULL,
    cluster_id TEXT NOT NULL,
    kind TEXT NOT NULL,
    name TEXT NOT NULL,
    status TEXT NOT NULL,
    started_at REAL NOT NULL,
    finished_at REAL NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_spans_op ON spans (op_id);
CREATE INDEX IF NOT EXISTS idx_spans_cluster ON spans (cluster_id);
CREATE INDEX IF NOT EXISTS idx_spans_kind ON spans (kind);
