-- Live platform telemetry (docs/observability.md "Events and live
-- telemetry"). Two halves:
--
-- 1. The events table (001) grows from a cluster-scoped UI timeline into
--    the durable EVENT BUS: every journal transition (op open/phase/
--    close/interrupt), watchdog escalation, fencing rejection, slice
--    incident, queue state change and fleet wave verdict lands one
--    structured row, written in the SAME transaction as the state change
--    it describes. `kind` is the machine-readable stream key
--    ('' = a legacy row predating the bus); op_id/tenant mirror the
--    correlation ids so the SSE feed's filters run on indexed SQL, and
--    sqlite's rowid is the stream cursor (`Last-Event-ID`).
ALTER TABLE events ADD COLUMN kind TEXT NOT NULL DEFAULT '';
ALTER TABLE events ADD COLUMN op_id TEXT NOT NULL DEFAULT '';
ALTER TABLE events ADD COLUMN tenant TEXT NOT NULL DEFAULT '';
CREATE INDEX IF NOT EXISTS idx_events_kind ON events (kind, created_at);

-- 2. Per-step training telemetry: a bounded ring of metric samples per
--    workload operation (loss / step wall-clock / steps-per-s / TFLOP/s /
--    MFU, plus checkpoint-save markers), fed from the train loop's
--    on_step seam and flushed with the span buffer. loss/step_s mirror
--    into real columns so the /metrics histograms scrape without JSON
--    hydration; the ring keeps the NEWEST observability.max_samples_per_op
--    rows per op.
CREATE TABLE IF NOT EXISTS metric_samples (
    id TEXT PRIMARY KEY,
    op_id TEXT NOT NULL,
    step INTEGER NOT NULL,
    kind TEXT NOT NULL,
    tenant TEXT NOT NULL,
    loss REAL NOT NULL,
    step_s REAL NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_metric_samples_op
    ON metric_samples (op_id, step);
