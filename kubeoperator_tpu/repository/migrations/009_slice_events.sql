-- Per-slice incident ledger (docs/resilience.md "Slice preemption"): one
-- row per slice-lifecycle event (detected / drained / degraded / replaced
-- / restored), written by the slice pool (resilience/slicepool.py) and the
-- watchdog's detection path. Separate from the operations journal because
-- an incident spans detection + the replace operation + the restore
-- verdict, possibly across controllers; op_id joins back to the journal.
CREATE TABLE IF NOT EXISTS slice_events (
    id TEXT PRIMARY KEY,
    cluster_id TEXT NOT NULL,
    slice_id INTEGER NOT NULL,
    kind TEXT NOT NULL,
    op_id TEXT NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_slice_events_cluster
    ON slice_events (cluster_id);
CREATE INDEX IF NOT EXISTS idx_slice_events_op ON slice_events (op_id);
