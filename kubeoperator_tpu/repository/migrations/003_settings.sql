-- Named system-settings documents (message-center channels first) — the
-- runtime-editable tier above app.yaml (SURVEY.md §5.6 config tiers).
CREATE TABLE IF NOT EXISTS settings (
    id TEXT PRIMARY KEY,
    name TEXT NOT NULL UNIQUE,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
