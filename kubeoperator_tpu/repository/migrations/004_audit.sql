-- Operation audit log (who did what against the platform API) —
-- reference parity: the operation-log screen; SURVEY.md §1 multi-tenancy.
CREATE TABLE IF NOT EXISTS audit_log (
    id TEXT PRIMARY KEY,
    user_name TEXT NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_audit_created ON audit_log (created_at);
