-- 001: initial schema (reference parity: migration/ SQL at boot,
-- SURVEY.md §2.1 row 1e; entity set per §2.2).
-- Query columns are real; the full entity document lives in `data` (JSON).

CREATE TABLE IF NOT EXISTS credentials (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS regions (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  provider TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS zones (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  region_id TEXT NOT NULL REFERENCES regions(id),
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS plans (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  provider TEXT NOT NULL,
  accelerator TEXT NOT NULL DEFAULT 'none',
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS hosts (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  ip TEXT NOT NULL,
  cluster_id TEXT NOT NULL DEFAULT '',
  status TEXT NOT NULL DEFAULT 'Pending',
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS clusters (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  project_id TEXT NOT NULL DEFAULT '',
  phase TEXT NOT NULL DEFAULT 'Initializing',
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS nodes (
  id TEXT PRIMARY KEY,
  name TEXT NOT NULL,
  cluster_id TEXT NOT NULL REFERENCES clusters(id),
  host_id TEXT NOT NULL,
  role TEXT NOT NULL,
  status TEXT NOT NULL DEFAULT 'Pending',
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL,
  UNIQUE(cluster_id, name)
);

CREATE TABLE IF NOT EXISTS backup_accounts (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS backup_strategies (
  id TEXT PRIMARY KEY,
  cluster_id TEXT UNIQUE NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS backup_files (
  id TEXT PRIMARY KEY,
  cluster_id TEXT NOT NULL,
  name TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS projects (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS project_members (
  id TEXT PRIMARY KEY,
  project_id TEXT NOT NULL REFERENCES projects(id),
  user_id TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL,
  UNIQUE(project_id, user_id)
);

CREATE TABLE IF NOT EXISTS users (
  id TEXT PRIMARY KEY,
  name TEXT UNIQUE NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS events (
  id TEXT PRIMARY KEY,
  cluster_id TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);
CREATE INDEX IF NOT EXISTS idx_events_cluster ON events(cluster_id, created_at);

CREATE TABLE IF NOT EXISTS messages (
  id TEXT PRIMARY KEY,
  user_id TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);

CREATE TABLE IF NOT EXISTS task_log_chunks (
  id TEXT PRIMARY KEY,
  cluster_id TEXT NOT NULL,
  task_id TEXT NOT NULL,
  seq INTEGER NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL
);
CREATE INDEX IF NOT EXISTS idx_logs_task ON task_log_chunks(task_id, seq);

CREATE TABLE IF NOT EXISTS components (
  id TEXT PRIMARY KEY,
  cluster_id TEXT NOT NULL,
  name TEXT NOT NULL,
  data TEXT NOT NULL,
  created_at REAL, updated_at REAL,
  UNIQUE(cluster_id, name)
);
