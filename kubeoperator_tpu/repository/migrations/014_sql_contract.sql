-- SQL contract (docs/resilience.md "SQL contract"): indexes the KO-S003
-- index-coverage rule surfaced on the hot telemetry tables.
--
-- metric_samples: the step histogram (step_rows) and the loss gauge
-- (latest_losses) both filter on kind = 'step' every /metrics scrape,
-- but the only index led with op_id — a full scan per scrape at
-- bus-scale row counts. (kind, step_s) serves the histogram's
-- kind + step_s > 0 predicate pair directly.
CREATE INDEX IF NOT EXISTS idx_metric_samples_kind
    ON metric_samples (kind, step_s);

-- workload_queue: the queue-wait histogram (wait_rows) filters on
-- started_at > 0 per scrape; no index led with it.
CREATE INDEX IF NOT EXISTS idx_workload_queue_started
    ON workload_queue (started_at);
