-- Fleet at 1000 (docs/resilience.md "Fleet operations"): `fleet status`
-- and the 1 Hz poll must answer over a 1000-rollout history without
-- hydrating every historical op's vars blob (a fleet op's vars carry the
-- whole wave ledger — hundreds of cluster names each). `summary` mirrors
-- a compact JSON digest (fleet/planner.py rollout_summary: counts +
-- circuit state only) maintained by the wave engine at every ledger
-- save; '' = the op predates the column or carries no digest. The
-- (kind, created_at) index makes newest-of-kind resolution one indexed
-- probe — the same mirrored-column trick as workload_queue (011).
ALTER TABLE operations ADD COLUMN summary TEXT NOT NULL DEFAULT '';
CREATE INDEX IF NOT EXISTS idx_operations_kind
    ON operations (kind, created_at);
