-- Operation journal (crash-safe lifecycle record): one row per lifecycle
-- operation (create, scale, upgrade, backup, recovery, terminate, ...),
-- opened BEFORE the phase loop starts and closed on success/failure — so a
-- controller killed mid-operation leaves a durable open row the boot
-- reconciler can sweep instead of a cluster stranded in an in-flight phase.
CREATE TABLE IF NOT EXISTS operations (
    id TEXT PRIMARY KEY,
    cluster_id TEXT NOT NULL,
    kind TEXT NOT NULL,
    status TEXT NOT NULL,
    data TEXT NOT NULL,
    created_at REAL NOT NULL,
    updated_at REAL NOT NULL
);
CREATE INDEX IF NOT EXISTS idx_operations_cluster ON operations (cluster_id);
CREATE INDEX IF NOT EXISTS idx_operations_status ON operations (status);
