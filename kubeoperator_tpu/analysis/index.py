"""ProjectIndex — the v2 engine's shared substrate: every package python
file parsed ONCE, reduced to serializable per-file facts, cached by content
hash.

Three consumers ride the same walk:

* per-file rules (astcheck KO-P001..P007 minus the retired P003, flow
  KO-P009) run against the freshly parsed tree and their findings are
  cached next to the facts;
* the guarded-by engine (flow.py KO-P008) consumes `ClassFacts` — lock
  attributes, per-method attribute writes with the lexically-held lock
  set, and the self-call graph — joined PROJECT-WIDE so inheritance and
  call-context propagation cross file boundaries;
* the contract rules (contracts.py KO-X009/KO-X010) consume the config
  read sites and the REST/CLI surface facts.

The cache is the reason full-repo `koctl lint` stays inside the tier-1
gate's 5 s budget as rules multiply: a warm run re-hashes files (cheap)
and re-runs only the project-wide joins (pure in-memory); only changed
files are re-parsed. `--changed` goes one step further and trusts git for
the unchanged set.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field

from kubeoperator_tpu.version import __version__

# Cache format version: bump when fact extraction changes shape, so a stale
# cache from an older analyzer can never masquerade as fresh facts.
CACHE_SCHEMA = 4

_SKIP_DIRS = {"content", "__pycache__"}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                   "BoundedSemaphore"}
# `_lock`, `lock`, `_ops_lock`, `write_lock`, ... — NOT `lock_timeout`
_LOCK_NAME_RE = re.compile(r"^_?(?:[a-z0-9_]+_)?lock$")

_CONFIG_KEY_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
# receiver names that mean "the process config object"
_CONFIG_RECEIVERS = {"config", "cfg"}


def iter_python_files(root: str):
    for base, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(base, fn)


def file_sha(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def _self_attr(node) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _dotted(node) -> str:
    """Best-effort dotted spelling of an expression (`self.s.config` ->
    "self.s.config"); "" when any link is not a Name/Attribute."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ------------------------------------------------------------ class facts --
@dataclass
class WriteSite:
    """One `self.<attr> = ...` (or augmented) write inside a method."""

    attr: str
    line: int
    locks: tuple     # lock attr names lexically held at the write
    in_closure: bool  # written from a nested def (runs on a caller thread)

    def to_dict(self) -> dict:
        return {"attr": self.attr, "line": self.line,
                "locks": list(self.locks), "in_closure": self.in_closure}

    @classmethod
    def from_dict(cls, d: dict) -> "WriteSite":
        return cls(d["attr"], d["line"], tuple(d["locks"]), d["in_closure"])


@dataclass
class MethodFacts:
    name: str
    line: int
    writes: list = field(default_factory=list)       # [WriteSite]
    self_calls: list = field(default_factory=list)   # [(callee, locks, line)]

    def to_dict(self) -> dict:
        return {"name": self.name, "line": self.line,
                "writes": [w.to_dict() for w in self.writes],
                "self_calls": [[c, list(l), ln]
                               for c, l, ln in self.self_calls]}

    @classmethod
    def from_dict(cls, d: dict) -> "MethodFacts":
        m = cls(d["name"], d["line"])
        m.writes = [WriteSite.from_dict(w) for w in d["writes"]]
        m.self_calls = [(c, tuple(l), ln) for c, l, ln in d["self_calls"]]
        return m


@dataclass
class ClassFacts:
    name: str
    file: str        # path relative to the analysis root's parent
    line: int
    bases: list = field(default_factory=list)
    lock_attrs: list = field(default_factory=list)
    methods: dict = field(default_factory=dict)      # name -> MethodFacts

    def to_dict(self) -> dict:
        return {"name": self.name, "file": self.file, "line": self.line,
                "bases": self.bases, "lock_attrs": self.lock_attrs,
                "methods": {k: m.to_dict() for k, m in self.methods.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "ClassFacts":
        c = cls(d["name"], d["file"], d["line"], d["bases"], d["lock_attrs"])
        c.methods = {k: MethodFacts.from_dict(m)
                     for k, m in d["methods"].items()}
        return c


class _MethodScanner(ast.NodeVisitor):
    """Collect writes + self-calls for one method, tracking the lexically
    held lock set through `with self.<lock>:` blocks. Nested defs ARE
    descended into (unlike the retired KO-P003): a closure's bare write to
    a guarded field races no matter which thread runs it — but the site is
    marked `in_closure` so the inference can treat its lock context as
    unknown rather than inheriting the enclosing method's."""

    def __init__(self, lock_attrs: set) -> None:
        self.lock_attrs = lock_attrs
        self.held: list = []
        self.closure_depth = 0
        self.writes: list = []
        self.self_calls: list = []

    def _locks(self) -> tuple:
        return tuple(sorted(set(self.held)))

    def visit_FunctionDef(self, node):  # noqa: N802
        self.closure_depth += 1
        # a closure starts with NO inherited lock: it runs when called,
        # not where it was defined
        saved, self.held = self.held, []
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved
        self.closure_depth -= 1

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815

    def visit_With(self, node):  # noqa: N802
        holds = [a for item in node.items
                 if (a := _self_attr(item.context_expr)) in self.lock_attrs]
        self.held.extend(holds)
        for stmt in node.body:
            self.visit(stmt)
        for item in node.items:
            self.visit(item.context_expr)
        for _ in holds:
            self.held.pop()

    def _record(self, target, lineno: int) -> None:
        attr = _self_attr(target)
        if attr and attr not in self.lock_attrs:
            self.writes.append(WriteSite(
                attr, lineno, self._locks(), self.closure_depth > 0))

    def visit_Assign(self, node):  # noqa: N802
        for target in node.targets:
            self._record(target, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):  # noqa: N802
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):  # noqa: N802
        callee = _self_attr(node.func)
        if callee:
            self.self_calls.append((callee, self._locks(), node.lineno))
        self.generic_visit(node)


def _lock_attrs_of_class(cls: ast.ClassDef) -> set:
    """Attributes assigned a threading lock/condition anywhere in the
    class, plus lock-NAMED attributes regardless of what they're assigned
    (`self._lock = lock` injection / aliasing must still arm the
    detector)."""
    locks: set = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        factory = ""
        if isinstance(node.value, ast.Call):
            func = node.value.func
            factory = (func.attr if isinstance(func, ast.Attribute)
                       else func.id if isinstance(func, ast.Name) else "")
        for target in node.targets:
            attr = _self_attr(target)
            if attr and (factory in _LOCK_FACTORIES
                         or _LOCK_NAME_RE.match(attr)):
                locks.add(attr)
    return locks


def _class_facts(cls: ast.ClassDef, rel: str) -> ClassFacts:
    facts = ClassFacts(
        name=cls.name, file=rel, line=cls.lineno,
        bases=[_dotted(b).rsplit(".", 1)[-1]
               for b in cls.bases if _dotted(b)],
        lock_attrs=sorted(_lock_attrs_of_class(cls)),
    )
    for method in cls.body:
        if not isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        scanner = _MethodScanner(set(facts.lock_attrs))
        for stmt in method.body:
            scanner.visit(stmt)
        m = MethodFacts(method.name, method.lineno)
        m.writes = scanner.writes
        m.self_calls = scanner.self_calls
        facts.methods[method.name] = m
    return facts


# ----------------------------------------------------------- config reads --
def _section_defaults(tree: ast.AST) -> dict:
    """Map each function's name -> its `section` keyword default, for
    resolving the `config.get(f"{section}.key", ...)` from_config idiom."""
    out: dict = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        args = node.args
        names = [a.arg for a in args.args]
        for name, default in zip(reversed(names), reversed(args.defaults)):
            if name == "section" and isinstance(default, ast.Constant) \
                    and isinstance(default.value, str):
                out[node.name] = default.value
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if arg.arg == "section" and isinstance(default, ast.Constant) \
                    and isinstance(default.value, str):
                out[node.name] = default.value
    return out


def _resolve_key(arg, section_default: str | None) -> str | None:
    """The dotted config key an expression names, if statically known."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value if _CONFIG_KEY_RE.match(arg.value) else None
    if isinstance(arg, ast.JoinedStr) and section_default:
        # f"{section}.rest" — exactly one formatted value, a Name 'section'
        parts: list[str] = []
        for value in arg.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            elif isinstance(value, ast.FormattedValue) and \
                    isinstance(value.value, ast.Name) and \
                    value.value.id == "section":
                parts.append(section_default)
            else:
                return None
        key = "".join(parts)
        return key if _CONFIG_KEY_RE.match(key) else None
    return None


def _config_reads(tree: ast.AST) -> list:
    """[(dotted_key, line)] for every statically-resolvable config read:
    `<...>.config.get("a.b.c", ...)` / `cfg.get("a.b", ...)` / the
    from_config `f"{section}.key"` idiom."""
    sections = _section_defaults(tree)
    reads: list = []
    # parent function tracking: walk functions explicitly
    def scan(node, func_name: str | None):
        for child in ast.iter_child_nodes(node):
            name = func_name
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            if isinstance(child, ast.Call) and \
                    isinstance(child.func, ast.Attribute) and \
                    child.func.attr == "get" and child.args:
                receiver = _dotted(child.func.value)
                if receiver.rsplit(".", 1)[-1] in _CONFIG_RECEIVERS:
                    key = _resolve_key(
                        child.args[0],
                        sections.get(name or "", None) if name else None)
                    if key:
                        reads.append((key, child.lineno))
            scan(child, name)

    scan(tree, None)
    return reads


# ---------------------------------------------------------- surface facts --
_ROUTE_ADDERS = {"add_get": "GET", "add_post": "POST", "add_put": "PUT",
                 "add_delete": "DELETE"}


def _fstring_template(node) -> str | None:
    """Normalize a route path expression to a template: constants verbatim,
    each formatted value -> "{p}". Query strings are stripped."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        text = node.value
    elif isinstance(node, ast.JoinedStr):
        parts = []
        for value in node.values:
            if isinstance(value, ast.Constant):
                parts.append(str(value.value))
            elif isinstance(value, ast.FormattedValue):
                parts.append("{p}")
            else:
                return None
        text = "".join(parts)
    else:
        return None
    return text.partition("?")[0]


def _surface_facts(tree: ast.AST) -> dict:
    """REST/CLI surface facts for KO-X010, extracted generically so the
    same walk serves api/server.py (routes), cli/koctl.py (rest calls +
    local dispatch + top-level commands) and fixture files alike."""
    routes: list = []        # [(method, template, line)]
    rest_calls: list = []    # [(method, template, line)]
    dispatch: list = []      # [(method, template, line)]
    commands: list = []      # [(name, line)] — top-level koctl subcommands
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute):
            continue
        # routes: r.add_get("/api/v1/...", handler) on any receiver
        if func.attr in _ROUTE_ADDERS and node.args:
            template = _fstring_template(node.args[0])
            if template and template.startswith("/api/"):
                routes.append((_ROUTE_ADDERS[func.attr], template,
                               node.lineno))
        # the CRUD helper: h._crud_routes(app, "/api/v1/plans", ...)
        elif func.attr == "_crud_routes" and len(node.args) >= 2:
            template = _fstring_template(node.args[1])
            if template and template.startswith("/api/"):
                for method, suffix in (("GET", ""), ("POST", ""),
                                       ("GET", "/{name}"),
                                       ("DELETE", "/{name}")):
                    routes.append((method, template + suffix, node.lineno))
        # transport calls: client.call("GET", f"/api/v1/...")
        elif func.attr == "call" and len(node.args) >= 2 and \
                isinstance(node.args[0], ast.Constant):
            method = node.args[0].value
            template = _fstring_template(node.args[1])
            if isinstance(method, str) and template and \
                    template.startswith("/api/"):
                rest_calls.append((method, template, node.lineno))
        # top-level koctl subcommands: sub.add_parser("name", ...)
        elif func.attr == "add_parser" and node.args and \
                isinstance(func.value, ast.Name) and func.value.id == "sub" \
                and isinstance(node.args[0], ast.Constant):
            commands.append((node.args[0].value, node.lineno))
    # LocalClient._dispatch match-case patterns
    for node in ast.walk(tree):
        if not isinstance(node, ast.Match):
            continue
        for case in node.cases:
            pattern = case.pattern
            if not isinstance(pattern, ast.MatchSequence) or \
                    len(pattern.patterns) != 2:
                continue
            method_pat, parts_pat = pattern.patterns
            if not (isinstance(method_pat, ast.MatchValue)
                    and isinstance(method_pat.value, ast.Constant)
                    and isinstance(parts_pat, ast.MatchSequence)):
                continue
            segments = []
            ok = True
            for part in parts_pat.patterns:
                if isinstance(part, ast.MatchValue) and \
                        isinstance(part.value, ast.Constant):
                    segments.append(str(part.value.value))
                elif isinstance(part, ast.MatchAs) and part.pattern is None \
                        and part.name:
                    segments.append("{p}")
                else:
                    ok = False
                    break
            if ok:
                dispatch.append((
                    method_pat.value.value,
                    "/api/v1/" + "/".join(segments),
                    case.pattern.lineno,
                ))
    return {"routes": routes, "rest_calls": rest_calls,
            "dispatch": dispatch, "commands": commands}


# -------------------------------------------------------------- file facts --
@dataclass
class FileFacts:
    """Everything the project-wide rules need from one file — JSON-plain so
    a warm cache run never re-parses the file."""

    rel: str
    classes: list = field(default_factory=list)     # [ClassFacts]
    config_reads: list = field(default_factory=list)  # [(key, line)]
    surface: dict = field(default_factory=dict)
    sql: dict = field(default_factory=dict)  # sqlmodel.extract_sql_facts

    def to_dict(self) -> dict:
        return {"rel": self.rel,
                "classes": [c.to_dict() for c in self.classes],
                "config_reads": [list(r) for r in self.config_reads],
                "surface": self.surface,
                "sql": self.sql}

    @classmethod
    def from_dict(cls, d: dict) -> "FileFacts":
        f = cls(d["rel"])
        f.classes = [ClassFacts.from_dict(c) for c in d["classes"]]
        f.config_reads = [tuple(r) for r in d["config_reads"]]
        f.surface = d["surface"]
        f.sql = d.get("sql", {})
        return f


def extract_file_facts(tree: ast.AST, rel: str) -> FileFacts:
    # local import: sqlmodel imports repository.db for the seam values,
    # and index.py must stay importable before the package fully loads
    from kubeoperator_tpu.analysis.sqlmodel import extract_sql_facts

    facts = FileFacts(rel=rel)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            facts.classes.append(_class_facts(node, rel))
    facts.config_reads = _config_reads(tree)
    facts.surface = _surface_facts(tree)
    facts.sql = extract_sql_facts(tree, rel)
    return facts


# ------------------------------------------------------------------- cache --
def default_cache_dir() -> str:
    base = os.environ.get("KO_ANALYZE_CACHE_DIR")
    if base:
        return base
    xdg = os.environ.get("XDG_CACHE_HOME",
                         os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(xdg, "ko-analyze")


class AnalysisCache:
    """Content-hash incremental cache: per python file {sha, facts,
    findings-by-rule}; one whole-tree entry for the artifact rules.

    Keyed by analyzer version + schema so upgrades self-invalidate. All
    failures degrade to a cold run — a broken cache must never break the
    gate (exit 2) or, worse, fake a clean one."""

    def __init__(self, cache_dir: str, root: str) -> None:
        self.root = root
        os.makedirs(cache_dir, exist_ok=True)
        digest = hashlib.sha256(
            os.path.abspath(root).encode()).hexdigest()[:16]
        self.path = os.path.join(cache_dir, f"index-{digest}.json")
        self.data: dict = {"schema": CACHE_SCHEMA, "version": __version__,
                           "files": {}, "artifacts": {}}
        self.hits = 0
        self.misses = 0
        try:
            with open(self.path, encoding="utf-8") as f:
                loaded = json.load(f)
            if loaded.get("schema") == CACHE_SCHEMA and \
                    loaded.get("version") == __version__:
                self.data = loaded
        except (OSError, ValueError):
            pass

    # ---- per-file ----
    def lookup(self, rel: str, sha: str) -> dict | None:
        """Entry for `rel` if its content hash still matches. There is
        deliberately NO trust-without-hashing mode: the cache is not
        keyed to git state, so 'git status clean' cannot prove an entry
        fresh (commit/branch-switch/revert all change content without
        dirtying the worktree) — and hashing the package is ~30 ms."""
        entry = self.data["files"].get(rel)
        if entry is None or entry["sha"] != sha:
            self.misses += 1
            return None
        self.hits += 1
        return entry

    def store(self, rel: str, sha: str, facts: FileFacts,
              findings_by_rule: dict) -> None:
        previous = self.data["files"].get(rel)
        if previous is not None and previous.get("sha") == sha:
            # a --rules subset run must not shrink a fuller entry: merge
            # per-rule findings for the same content hash
            findings_by_rule = {**previous.get("findings", {}),
                                **findings_by_rule}
        self.data["files"][rel] = {
            "sha": sha,
            "facts": facts.to_dict(),
            "findings": findings_by_rule,
        }

    def prune(self, live_rels: set) -> None:
        """Drop entries for deleted files so their cached findings can't
        haunt future reports."""
        for rel in list(self.data["files"]):
            if rel not in live_rels:
                del self.data["files"][rel]

    # ---- whole-tree artifact entry ----
    def artifact_lookup(self, tree_sha: str) -> dict | None:
        entry = self.data["artifacts"]
        if entry.get("sha") != tree_sha:
            return None
        return entry

    def artifact_fast_entry(self, git_head: str, changed: set,
                            root: str) -> dict | None:
        """The --changed shortcut around the whole-tree hash. Sound only
        when git can vouch for the artifact inputs: the cache was saved
        at the SAME commit with a then-clean package tree, nothing under
        the package is dirty now, and the cached entry was built without
        --plan files (whose findings would otherwise replay into a
        plan-less run)."""
        meta = self.data.get("git") or {}
        entry = self.data["artifacts"]
        if (not git_head or meta.get("head") != git_head
                or meta.get("dirty")                       # dirty at save
                or entry.get("plans") != []
                or entry.get("findings") is None
                or any(p.startswith(root + os.sep) for p in changed)):
            return None
        return entry

    def artifact_store(self, tree_sha: str, findings_by_rule: dict,
                       files_scanned: int, plans: tuple = ()) -> None:
        self.data["artifacts"] = {"sha": tree_sha,
                                  "findings": findings_by_rule,
                                  "files_scanned": files_scanned,
                                  "plans": sorted(plans)}

    def record_git_state(self, git_head: str, changed: set,
                         root: str) -> None:
        """Called only when the run actually asked git (--changed): pin
        the cache to (HEAD, dirty-under-root). Runs that didn't ask git
        clear the pin instead — an unknown state must never vouch."""
        if git_head:
            self.data["git"] = {
                "head": git_head,
                "dirty": sorted(os.path.relpath(p, os.path.dirname(root))
                                for p in changed
                                if p.startswith(root + os.sep)),
            }
        else:
            self.data.pop("git", None)

    def save(self) -> None:
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(self.data, f)
            os.replace(tmp, self.path)
        except OSError:
            pass


def tree_sha(root: str) -> str:
    """One hash over every non-cache file under root (names + contents):
    the artifact rules' invalidation key. Content files are few thousand
    small text files; this stays ~100 ms."""
    h = hashlib.sha256()
    for base, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for fn in sorted(files):
            path = os.path.join(base, fn)
            h.update(os.path.relpath(path, root).encode())
            try:
                with open(path, "rb") as f:
                    h.update(hashlib.sha256(f.read()).digest())
            except OSError:
                h.update(b"<unreadable>")
    return h.hexdigest()


# ------------------------------------------------------------------- index --
@dataclass
class ProjectIndex:
    """The project-wide join surface: all per-file facts, by rel path."""

    root: str
    files: dict = field(default_factory=dict)   # rel -> FileFacts

    def all_classes(self) -> list:
        return [c for f in self.files.values() for c in f.classes]

    def config_reads(self) -> list:
        """[(key, rel, line)] across the project."""
        return [(key, rel, line)
                for rel, facts in sorted(self.files.items())
                for key, line in facts.config_reads]

    def surface(self, what: str) -> list:
        """[(method/name, template/line, rel, line)] for one surface kind
        across the project ('routes' | 'rest_calls' | 'dispatch' |
        'commands')."""
        out = []
        for rel, facts in sorted(self.files.items()):
            for row in facts.surface.get(what, ()):
                out.append((*row, rel))
        return out


def git_head(repo_dir: str) -> str:
    """Current HEAD commit sha, or "" when git state is unreadable."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", repo_dir, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return ""
    return out.stdout.strip() if out.returncode == 0 else ""


def git_changed_files(repo_dir: str) -> set | None:
    """Paths (absolute) git reports as modified/added/untracked, or None
    when git state can't be read (not a repo, no git binary) — callers
    must fall back to a full scan, never assume 'nothing changed'."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", repo_dir, "status", "--porcelain"],
            capture_output=True, text=True, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    top = subprocess.run(
        ["git", "-C", repo_dir, "rev-parse", "--show-toplevel"],
        capture_output=True, text=True, timeout=10,
    )
    if top.returncode != 0:
        return None
    base = top.stdout.strip()
    changed = set()
    for line in out.stdout.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].split(" -> ")[-1].strip().strip('"')
        changed.add(os.path.abspath(os.path.join(base, path)))
    return changed
