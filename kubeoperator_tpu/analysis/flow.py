"""Per-function dataflow + the two flow rule families.

`_PathInterp` is a small abstract interpreter over a function body: it
pushes a finite set of abstract states through every statement, modelling
branches (all arms), loops (to a fixed point — the lattice is tiny),
`try/except/finally` (handlers see the union of states reachable anywhere
in the try body; finallies run on every path), `return`, `raise`, `break`
and `continue`. That is exactly enough machinery for:

* KO-P009 (exception-flow discipline):
  - a `journal.open()` whose result stays function-local must reach a
    `close()`/`interrupt()` on every path that completes normally —
    exiting by EXCEPTION is fine (propagation IS the reraise the journal
    contract allows: the op stays open for the boot reconciler to sweep),
    but a `return` or fall-off-the-end with the op still open is a leak
    that records the operation as Running forever. Ownership transfers
    stop the tracking: `return op`, `nonlocal`/`global` targets, storing
    into an attribute/subscript.
  - no handler that catches `BaseException` (explicitly or via a bare
    `except:`) may swallow it: chaos `ControllerDeath` derives from
    BaseException precisely so it tears through the stack like a real
    SIGKILL; a swallower turns the kill-the-controller drill into a
    silent no-op. The handler must re-raise on some path (or carry a
    `# KO-P009: waived — <reason>` comment).

* KO-P010 (span discipline) — the tracing layer's analog of KO-P009's
  journal tooth, over the same interpreter:
  - a `tracer.start_span(...)` (any receiver) whose result stays
    function-local must reach an `end_span(...)` naming it on every
    normally-completing path. Exiting by exception is fine — a Running
    span next to an interrupted operation is crash EVIDENCE, exactly
    like an open journal op — but a `return` or fall-off-the-end with
    the span still open leaks a span that reads Running forever and
    corrupts every duration histogram built over it. The same ownership
    escapes apply (`return span`, `nonlocal`, storing into an
    attribute/subscript).
  - `tracer.span(...)` (the context-manager form, receiver ending in
    `tracer`) must actually be used as a `with` context expression —
    called bare, the span starts and nothing ever ends it.
  Waive a deliberate leak with `# KO-P010: waived — <reason>`.

* KO-P008 (guarded-by inference) — not an interpreter client but the
  same module's other half: infer each attribute's lock set from its
  write sites PROJECT-WIDE over the index's ClassFacts, propagating
  lock-held context through self-calls to a fixed point and joining
  subclasses with the base class that owns the lock. Supersedes the
  retired single-file KO-P003 heuristic.
"""

from __future__ import annotations

import ast
import os

from kubeoperator_tpu.analysis.index import ProjectIndex, _dotted
from kubeoperator_tpu.analysis.report import Finding

_P009_WAIVER = "KO-P009: waived"


# =========================================================================
# the interpreter
# =========================================================================
class BlockResult:
    """States leaving a statement block, by exit kind. Each kind holds a
    set of frozensets (the abstract states)."""

    def __init__(self) -> None:
        self.normal: set = set()
        self.raised: set = set()
        self.breaks: set = set()
        self.continues: set = set()


class _PathInterp:
    """Pushes sets of frozenset-states through a function body.

    The client provides `transfer(stmt, state) -> state` for straight-line
    effects and `on_exit(kind, state, node)` called at `return` sites and
    function fall-off. `raise` exits are NOT reported — propagating an
    exception is a legal exit for every current client."""

    def __init__(self, transfer, on_exit, escape=None) -> None:
        self.transfer = transfer
        self.on_exit = on_exit
        self.escape = escape or (lambda stmt, state: state)

    def run(self, body: list, entry: frozenset) -> None:
        result = self.exec_block(body, {entry})
        for state in result.normal:
            self.on_exit("end", state, None)

    # ---- core ----
    def exec_block(self, stmts: list, states: set) -> BlockResult:
        result = BlockResult()
        current = set(states)
        for stmt in stmts:
            if not current:
                break
            step = self.exec_stmt(stmt, current)
            result.raised |= step.raised
            result.breaks |= step.breaks
            result.continues |= step.continues
            current = step.normal
        result.normal = current
        return result

    def exec_stmt(self, stmt, states: set) -> BlockResult:
        result = BlockResult()
        if isinstance(stmt, ast.Return):
            for state in states:
                self.on_exit("return", state, stmt)
            return result
        if isinstance(stmt, ast.Raise):
            result.raised |= states
            return result
        if isinstance(stmt, ast.Break):
            result.breaks |= states
            return result
        if isinstance(stmt, ast.Continue):
            result.continues |= states
            return result
        if isinstance(stmt, ast.If):
            body = self.exec_block(stmt.body, states)
            orelse = self.exec_block(stmt.orelse, states)
            return self._merge(body, orelse)
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            return self._exec_loop(stmt, states)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # structural, like If/Try: the body is interpreted statement
            # by statement — handing the whole With to `transfer` would
            # let its ast.walk see a CONDITIONAL close deep in the body
            # and untrack the op on every path
            return self.exec_block(stmt.body, states)
        if isinstance(stmt, ast.Try):
            return self._exec_try(stmt, states)
        if isinstance(stmt, ast.Match):
            out = BlockResult()
            matched_all = False
            for case in stmt.cases:
                arm = self.exec_block(case.body, states)
                out = self._merge(out, arm)
                if isinstance(case.pattern, ast.MatchAs) and \
                        case.pattern.pattern is None:
                    matched_all = True
            if not matched_all:
                out.normal |= states   # no arm may match
            return out
        # straight-line statement: apply the transfer function. Any
        # statement may ALSO raise — modelled at the try level, where the
        # union of in-body states feeds the handlers.
        result.normal = {self.transfer(stmt, s) for s in states}
        return result

    def _merge(self, a: BlockResult, b: BlockResult) -> BlockResult:
        out = BlockResult()
        out.normal = a.normal | b.normal
        out.raised = a.raised | b.raised
        out.breaks = a.breaks | b.breaks
        out.continues = a.continues | b.continues
        return out

    def _exec_loop(self, stmt, states: set) -> BlockResult:
        result = BlockResult()
        # `while True:` (literal) has no zero-iteration path and never
        # exhausts: its ONLY normal exits are breaks — without this, a
        # span/journal obligation opened before an infinite retry loop
        # that exits by return/raise inside the body would be flagged on
        # a fall-through path that cannot execute
        infinite = (isinstance(stmt, ast.While)
                    and isinstance(stmt.test, ast.Constant)
                    and bool(stmt.test.value))
        seen: set = set(states)     # zero-iteration path (finite loops)
        frontier = set(states)
        for _ in range(8):          # tiny lattice: converges in 2-3
            step = self.exec_block(stmt.body, frontier)
            result.raised |= step.raised
            new = (step.normal | step.continues) - seen
            result.normal |= step.breaks
            seen |= new
            if not new:
                break
            frontier = new
        orelse = self.exec_block(stmt.orelse, set() if infinite else seen)
        result.raised |= orelse.raised
        result.normal |= orelse.normal
        result.breaks |= orelse.breaks
        result.continues |= orelse.continues
        return result

    def _exec_try(self, stmt: ast.Try, states: set) -> BlockResult:
        body = self.exec_block(stmt.body, states)
        # any state reachable anywhere inside the try body may be live
        # when an exception transfers to a handler
        inflight = set(states) | body.normal | body.raised
        handled = BlockResult()
        for handler in stmt.handlers:
            arm = self.exec_block(handler.body, inflight)
            handled = self._merge(handled, arm)
        orelse = self.exec_block(stmt.orelse, body.normal)
        out = BlockResult()
        out.normal = handled.normal | orelse.normal
        # body raises survive only if some exception type has no handler;
        # conservatively keep them — a missed close on a propagating path
        # is allowed anyway, so over-keeping raised states is harmless
        out.raised = handled.raised | orelse.raised | body.raised
        out.breaks = body.breaks | handled.breaks | orelse.breaks
        out.continues = body.continues | handled.continues | orelse.continues
        if stmt.finalbody:
            final_in = (out.normal | out.raised | out.breaks | out.continues)
            # the finally body's effects apply to every path; run it once
            # per incoming state and substitute the results per exit kind
            out.normal = self._through_final(stmt.finalbody, out.normal)
            out.raised = self._through_final(stmt.finalbody, out.raised)
            out.breaks = self._through_final(stmt.finalbody, out.breaks)
            out.continues = self._through_final(stmt.finalbody, out.continues)
            # return-through-finally: ast.Return inside try already called
            # on_exit before the finally's transfer could run. Clients that
            # need exact return-through-finally modelling register closes
            # seen in ANY finally via `escape` pre-pass instead.
            del final_in
        return out

    def _through_final(self, finalbody: list, states: set) -> set:
        if not states:
            return states
        step = self.exec_block(finalbody, states)
        return step.normal | step.raised


# =========================================================================
# KO-P009 — exception-flow discipline
# =========================================================================
def _call_of(node):
    """(receiver_dotted, attr) for a call expression, else None."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        return _dotted(node.func.value), node.func.attr
    return None


def _is_journal_receiver(receiver: str) -> bool:
    return receiver.split(".")[-1].endswith("journal")


_CLOSERS = {"close", "interrupt"}


def _stmt_call(stmt):
    """The top-level call of an Expr/Assign statement, if any."""
    if isinstance(stmt, ast.Expr):
        return stmt.value if isinstance(stmt.value, ast.Call) else None
    if isinstance(stmt, ast.Assign):
        return stmt.value if isinstance(stmt.value, ast.Call) else None
    return None


def _journal_open_findings(func, rel: str, rule: str) -> list:
    """Flag function-local journal ops that can complete normally while
    still open. See the module docstring for the ownership rules."""
    nonlocals: set = set()
    for stmt in ast.walk(func):
        if isinstance(stmt, (ast.Nonlocal, ast.Global)):
            nonlocals.update(stmt.names)

    # does this function even open a journal op into a local name?
    opens = False
    for node in ast.walk(func):
        call = _call_of(node)
        if call and call[1] == "open" and _is_journal_receiver(call[0]):
            opens = True
    if not opens:
        return []

    # a close anywhere in ANY finally body covers return-through-finally
    # (the interpreter reports returns before applying the finally)
    finally_closed: set = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Try) and node.finalbody:
            for sub in ast.walk(ast.Module(body=node.finalbody,
                                           type_ignores=[])):
                call = _call_of(sub)
                if call and call[1] in _CLOSERS and \
                        _is_journal_receiver(call[0]) and sub.args and \
                        isinstance(sub.args[0], ast.Name):
                    finally_closed.add(sub.args[0].id)

    findings: list = []
    reported: set = set()

    def transfer(stmt, state: frozenset) -> frozenset:
        out = set(state)
        # assignment of an open() result
        if isinstance(stmt, ast.Assign):
            call = _call_of(stmt.value)
            if call and call[1] == "open" and _is_journal_receiver(call[0]):
                target = stmt.targets[0]
                if isinstance(target, ast.Name) and \
                        target.id not in nonlocals:
                    out.add((target.id, stmt.value.lineno))
                # nonlocal / attribute / tuple targets: ownership escapes
                return frozenset(out)
            # reassigning a tracked name to something else: stop tracking
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    out = {(n, ln) for n, ln in out if n != target.id}
                # storing a tracked op into an attribute/subscript:
                # ownership escapes
                elif isinstance(target, (ast.Attribute, ast.Subscript)) and \
                        isinstance(stmt.value, ast.Name):
                    out = {(n, ln) for n, ln in out
                           if n != stmt.value.id}
        # close()/interrupt() on a tracked name
        for node in ast.walk(stmt):
            call = _call_of(node)
            if call and call[1] in _CLOSERS and \
                    _is_journal_receiver(call[0]) and node.args and \
                    isinstance(node.args[0], ast.Name):
                out = {(n, ln) for n, ln in out if n != node.args[0].id}
        return frozenset(out)

    def on_exit(kind, state: frozenset, node) -> None:
        open_ops = set(state)
        if kind == "return" and node is not None and \
                isinstance(node.value, ast.Name):
            # `return op` — ownership transfers to the caller
            open_ops = {(n, ln) for n, ln in open_ops
                        if n != node.value.id}
        for name, line in open_ops:
            if name in finally_closed or (name, line) in reported:
                continue
            reported.add((name, line))
            findings.append(Finding(
                rule, rel, line,
                f"journal op {name!r} opened in {func.name}() can complete "
                f"normally without close()/interrupt() — the operation row "
                f"stays Running forever; close on every non-raising path "
                f"or hand ownership out (return/nonlocal/store)",
            ))

    _PathInterp(transfer, on_exit).run(func.body, frozenset())
    return findings


def _mentions_base_exception(type_node) -> bool:
    if type_node is None:
        return True     # bare except:
    for sub in ast.walk(type_node):
        if isinstance(sub, ast.Name) and sub.id == "BaseException":
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "BaseException":
            return True
    return False


def _swallow_findings(tree: ast.AST, rel: str, rule: str,
                      source_lines: list) -> list:
    """`except BaseException` / bare `except:` handlers that never
    re-raise. KO-P005 warns on the bare spelling for style; THIS rule is
    the error-tier teeth: swallowing BaseException also swallows chaos
    ControllerDeath, KeyboardInterrupt and SystemExit."""
    findings: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not _mentions_base_exception(node.type):
            continue
        reraises = any(isinstance(sub, ast.Raise)
                       for sub in ast.walk(node))
        if reraises:
            continue
        lo = max(node.lineno - 2, 0)
        waived = any(_P009_WAIVER in line
                     for line in source_lines[lo:node.lineno + 1])
        if waived:
            continue
        findings.append(Finding(
            rule, rel, node.lineno,
            "handler catches BaseException and never re-raises — it would "
            "swallow chaos ControllerDeath (and KeyboardInterrupt/"
            "SystemExit); re-raise, narrow to Exception, or waive with "
            f"`# {_P009_WAIVER} — <reason>`",
        ))
    return findings


def check_exception_flow(root: str, tree: ast.AST, path: str,
                         source: str | None = None) -> list:
    """KO-P009 entry point, per file (same signature family as astcheck
    rules, plus the source text for waiver comments)."""
    rel = os.path.relpath(path, os.path.dirname(root) or ".")
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    lines = source.splitlines()
    findings = _swallow_findings(tree, rel, "KO-P009", lines)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_journal_open_findings(node, rel, "KO-P009"))
    return findings


# =========================================================================
# KO-P010 — span discipline
# =========================================================================
_P010_WAIVER = "KO-P010: waived"


def _waived_near(source_lines: list, lineno: int, marker: str) -> bool:
    lo = max(lineno - 3, 0)
    return any(marker in line for line in source_lines[lo:lineno + 1])


def _span_open_findings(func, rel: str, source_lines: list) -> list:
    """Flag function-local `start_span` results that can complete normally
    while still open — the journal-leak analysis (same interpreter, same
    ownership rules) retargeted at the tracing layer; see the module
    docstring."""
    nonlocals: set = set()
    for stmt in ast.walk(func):
        if isinstance(stmt, (ast.Nonlocal, ast.Global)):
            nonlocals.update(stmt.names)

    def is_open(node) -> bool:
        call = _call_of(node)
        return bool(call and call[1] == "start_span")

    if not any(is_open(node) for node in ast.walk(func)):
        return []

    # an end_span anywhere in ANY finally body covers return-through-finally
    finally_closed: set = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Try) and node.finalbody:
            for sub in ast.walk(ast.Module(body=node.finalbody,
                                           type_ignores=[])):
                call = _call_of(sub)
                if call and call[1] == "end_span" and sub.args and \
                        isinstance(sub.args[0], ast.Name):
                    finally_closed.add(sub.args[0].id)

    findings: list = []
    reported: set = set()

    def transfer(stmt, state: frozenset) -> frozenset:
        out = set(state)
        if isinstance(stmt, ast.Assign):
            if is_open(stmt.value):
                target = stmt.targets[0]
                if isinstance(target, ast.Name) and \
                        target.id not in nonlocals and \
                        not _waived_near(source_lines, stmt.lineno,
                                         _P010_WAIVER):
                    out.add((target.id, stmt.value.lineno))
                # nonlocal / attribute / tuple targets: ownership escapes
                return frozenset(out)
            # reassigning a tracked name (incl. a FRESH start_span into the
            # same name each loop iteration — the new one replaces the old
            # obligation); storing a tracked span into an attribute or
            # subscript hands ownership out
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    out = {(n, ln) for n, ln in out if n != target.id}
                elif isinstance(target, (ast.Attribute, ast.Subscript)) and \
                        isinstance(stmt.value, ast.Name):
                    out = {(n, ln) for n, ln in out
                           if n != stmt.value.id}
        # end_span(...) on a tracked name
        for node in ast.walk(stmt):
            call = _call_of(node)
            if call and call[1] == "end_span" and node.args and \
                    isinstance(node.args[0], ast.Name):
                out = {(n, ln) for n, ln in out if n != node.args[0].id}
        return frozenset(out)

    def on_exit(kind, state: frozenset, node) -> None:
        open_spans = set(state)
        if kind == "return" and node is not None and \
                isinstance(node.value, ast.Name):
            # `return span` — ownership transfers to the caller
            open_spans = {(n, ln) for n, ln in open_spans
                          if n != node.value.id}
        for name, line in open_spans:
            if name in finally_closed or (name, line) in reported:
                continue
            reported.add((name, line))
            findings.append(Finding(
                "KO-P010", rel, line,
                f"span {name!r} started in {func.name}() can complete "
                f"normally without end_span() — it reads Running forever "
                f"and corrupts the duration histograms; end it on every "
                f"non-raising path, hand ownership out "
                f"(return/nonlocal/store), or waive with "
                f"`# {_P010_WAIVER} — <reason>`",
            ))

    _PathInterp(transfer, on_exit).run(func.body, frozenset())
    return findings


def _bare_span_cm_findings(tree: ast.AST, rel: str,
                           source_lines: list) -> list:
    """`tracer.span(...)` (the context-manager form) called OUTSIDE a
    `with` item: the span starts, nothing ever ends it."""
    with_exprs: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                with_exprs.add(id(item.context_expr))
    findings: list = []
    for node in ast.walk(tree):
        call = _call_of(node)
        if not call or call[1] != "span":
            continue
        receiver = call[0]
        if not receiver.split(".")[-1].endswith("tracer"):
            continue
        if id(node) in with_exprs:
            continue
        if _waived_near(source_lines, node.lineno, _P010_WAIVER):
            continue
        findings.append(Finding(
            "KO-P010", rel, node.lineno,
            f"{receiver}.span(...) is a context manager but is not the "
            f"context expression of a `with` — the span starts and never "
            f"ends; use `with {receiver}.span(...):`, or start_span/"
            f"end_span explicitly, or waive with "
            f"`# {_P010_WAIVER} — <reason>`",
        ))
    return findings


def check_span_discipline(root: str, tree: ast.AST, path: str,
                          source: str | None = None) -> list:
    """KO-P010 entry point, per file (same signature family as KO-P009)."""
    rel = os.path.relpath(path, os.path.dirname(root) or ".")
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    lines = source.splitlines()
    findings = _bare_span_cm_findings(tree, rel, lines)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            findings.extend(_span_open_findings(node, rel, lines))
    return findings


# =========================================================================
# KO-P008 — guarded-by inference over the project index
# =========================================================================
def _lock_families(index: ProjectIndex) -> list:
    """Group each lock-owning class with its subclasses (single-level name
    resolution over the whole project): the subclass writes against the
    base class's lock discipline. Returns [(family_name, lock_attrs,
    [ClassFacts...])]."""
    classes = index.all_classes()
    by_name: dict = {}
    for cls in classes:
        by_name.setdefault(cls.name, cls)
    families = []
    for cls in classes:
        if not cls.lock_attrs:
            continue
        members = [cls]
        for other in classes:
            if other is cls:
                continue
            # walk up `other`'s base chain looking for cls
            seen = set()
            base_names = list(other.bases)
            while base_names:
                base = base_names.pop()
                if base in seen:
                    break
                seen.add(base)
                if base == cls.name:
                    members.append(other)
                    break
                parent = by_name.get(base)
                if parent is not None:
                    base_names.extend(parent.bases)
        families.append((cls.name, set(cls.lock_attrs), members))
    return families


def _exempt_method(name: str) -> bool:
    # conventions carried over from KO-P003: no concurrency before
    # __init__ completes; *_locked methods document "called with lock held"
    return name == "__init__" or name.endswith("_locked")


def check_guarded_by(index: ProjectIndex) -> list:
    """Infer each attribute's lock set from its write sites and flag mixed
    guarded/bare access, interprocedurally:

    * lock-held context propagates through `self.method()` calls to a
      fixed point — a private helper only ever invoked under the lock is
      guarded even with no lexical `with` of its own;
    * subclasses join the base class family, so an Executor subclass
      writing a base-guarded field bare is caught across files;
    * closure writes participate but never inherit the enclosing
      method's lexical lock (they run on whichever thread calls them).
    """
    findings: list = []
    for family_name, lock_attrs, members in _lock_families(index):
        # ---- collect per-method facts across the family ----
        methods: dict = {}            # name -> [(ClassFacts, MethodFacts)]
        for cls in members:
            for mname, mfacts in cls.methods.items():
                methods.setdefault(mname, []).append((cls, mfacts))

        # ---- fixed point: which methods can run with the lock held on
        # every observed entry, which can run bare ----
        # entry contexts: public methods (no leading _) get an implicit
        # bare seed (any thread may call them); private methods start
        # EMPTY — empty means "no entry known yet", never "bare": a
        # premature bare would stick (sets only grow) and flag correctly
        # locked multi-level helper chains. A call edge contributes
        # {"locked"} when the call site lexically holds the lock, else it
        # forwards the caller's own (currently known) entry contexts.
        locked_entry: dict = {}       # name -> {"locked", "bare"} contexts
        for mname in methods:
            locked_entry[mname] = set()
            if not mname.startswith("_") or _exempt_method(mname):
                locked_entry[mname].add("bare")
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for mname, impls in methods.items():
                for _cls, mfacts in impls:
                    caller_ctxs = set(locked_entry[mname])
                    for callee, locks, _line in mfacts.self_calls:
                        if callee not in locked_entry:
                            continue
                        if set(locks) & lock_attrs:
                            ctxs = {"locked"}
                        else:
                            # entry-"locked" means called-with-lock-held,
                            # so the whole body (incl. this call) runs
                            # under it; bare forwards as bare
                            ctxs = caller_ctxs
                        before = set(locked_entry[callee])
                        locked_entry[callee] |= ctxs
                        if locked_entry[callee] != before:
                            changed = True

        # a private method nobody in the family calls: unknown external
        # caller — treat as bare-capable (conservative)
        for mname, ctxs in locked_entry.items():
            if not ctxs:
                ctxs.add("bare")

        # ---- classify write sites ----
        guarded: dict = {}    # attr -> [(file, method, line)]
        bare: dict = {}
        for mname, impls in methods.items():
            if _exempt_method(mname):
                continue
            entry_bare = "bare" in locked_entry[mname]
            for cls, mfacts in impls:
                for w in mfacts.writes:
                    lexically = bool(set(w.locks) & lock_attrs)
                    if lexically:
                        guarded.setdefault(w.attr, []).append(
                            (cls.file, mname, w.line))
                    elif w.in_closure:
                        # a closure write with no lexical lock: context
                        # unknown — bare if the attr is guarded elsewhere
                        bare.setdefault(w.attr, []).append(
                            (cls.file, mname, w.line))
                    elif not entry_bare:
                        # every observed entry holds the lock
                        guarded.setdefault(w.attr, []).append(
                            (cls.file, mname, w.line))
                    else:
                        bare.setdefault(w.attr, []).append(
                            (cls.file, mname, w.line))

        for attr in sorted(set(guarded) & set(bare)):
            locked_at = ", ".join(
                f"{m}:{ln}" for _f, m, ln in sorted(guarded[attr])[:3])
            for file, method, line in sorted(bare[attr]):
                findings.append(Finding(
                    "KO-P008", file, line,
                    f"{family_name}.{attr} is lock-guarded at {locked_at} "
                    f"but written bare in {method}() — a write-write race; "
                    f"take {'/'.join(sorted(lock_attrs))} or rename the "
                    f"helper *_locked if it is documented as "
                    f"called-with-lock-held",
                ))
    return findings
