"""KO-S001..KO-S004 — the SQL rule family over the sqlmodel substrate.

The Postgres seam's enforcement layer (ROADMAP item 1): every statement
the extractor resolves is held to the migration-derived schema
(KO-S001), scanned for SQLite-isms outside the sanctioned seams
(KO-S002), and — on the hot mirrored-column tables — checked for index
coverage (KO-S003). The migration fold itself reports discipline
violations (KO-S004). All four run fresh each run: the corpus is 14
small .sql files plus facts the per-file cache already holds, so
`koctl lint --changed` re-checks SQL whenever a migration OR a
statement-bearing python file changes, at no measurable cost.
"""

from __future__ import annotations

import os
import re

from kubeoperator_tpu.analysis.report import Finding
from kubeoperator_tpu.analysis.sqlmodel import (
    DYNAMIC_MARK,
    SchemaModel,
    build_schema_model,
    iter_migration_statements,
    mask_strings,
    parse_statement,
    strip_sql_comments,
)

SQL_RULES = ("KO-S001", "KO-S002", "KO-S003", "KO-S004")

# KO-S003's universe: the mirrored-column tables the queue, event bus and
# metric-sample subsystems read at bus scale (PR-12..14)
HOT_TABLES = frozenset(
    {"operations", "events", "workload_queue", "metric_samples"})

# the one file whose PRAGMA statements are sanctioned: the handle itself
_PRAGMA_SEAM_SUFFIX = "repository/db.py"

# statement heads KO-S001/S003 have nothing to say about
_SKIP_HEADS = frozenset(
    {"BEGIN", "COMMIT", "ROLLBACK", "PRAGMA", "CREATE", "ALTER", "DROP"})

_DIALECT_PATTERNS = (
    (re.compile(r"\bjulianday\s*\(", re.IGNORECASE),
     "julianday() is SQLite-only clock SQL — interpolate the DB_NOW_SQL "
     "seam (repository/db.py) instead"),
    (re.compile(r"\bdatetime\s*\(", re.IGNORECASE),
     "datetime() is SQLite-only clock SQL — interpolate the DB_NOW_SQL "
     "seam (repository/db.py) instead"),
    (re.compile(r"\bstrftime\s*\(", re.IGNORECASE),
     "strftime() is SQLite-only clock SQL — interpolate the DB_NOW_SQL "
     "seam (repository/db.py) instead"),
    (re.compile(r"\bINSERT\s+OR\s+(?:REPLACE|IGNORE)\b", re.IGNORECASE),
     "INSERT OR REPLACE/IGNORE is SQLite-only — use ANSI "
     "INSERT ... ON CONFLICT"),
    (re.compile(r"\bPRAGMA\b", re.IGNORECASE),
     "PRAGMA is SQLite-only and sanctioned only inside repository/db.py"),
    (re.compile(r"(?<![\w.'])rowid\b", re.IGNORECASE),
     "bare rowid is SQLite-only — interpolate the ROWID_SQL cursor seam "
     "(repository/db.py) instead"),
)

# the four mirrored columns EVERY EntityRepo table carries beyond its
# declared mirror tuple (repos.py save() writes them unconditionally)
_ENTITY_BASE_COLUMNS = ("id", "data", "created_at", "updated_at")

_PREDICATE_RE = re.compile(
    r"(?<![\w.])([A-Za-z_]\w*)\s*(?:=|!=|<>|>=|<=|>|<)(?!=)")
_IN_LIKE_RE = re.compile(
    r"(?<![\w.])(NOT\s+)?([A-Za-z_]\w*)\s+(?:NOT\s+)?(?:IN|LIKE)\b",
    re.IGNORECASE)


def _statement_caption(st: dict) -> str:
    """First ~60 chars of the statement for finding messages."""
    text = " ".join(st["text"].replace(DYNAMIC_MARK, "<dyn>").split())
    return text if len(text) <= 60 else text[:57] + "..."


def _migrations_dir(root: str) -> str:
    return os.path.join(root, "repository", "migrations")


def _migration_rel(root: str, fname: str) -> str:
    parent = os.path.dirname(root) or "."
    return os.path.relpath(os.path.join(_migrations_dir(root), fname),
                           parent)


# ------------------------------------------------------------------ KO-S001 --
def _check_conformance_statement(model: SchemaModel, rel: str,
                                 st: dict) -> list:
    parsed = parse_statement(st["text"])
    if parsed["head"] not in ("SELECT", "INSERT", "UPDATE", "DELETE") \
            or not parsed["tables"]:
        return []
    findings = []
    caption = _statement_caption(st)
    known_tables = []
    for table in parsed["tables"]:
        if table not in model.tables:
            findings.append(Finding(
                "KO-S001", rel, st["line"],
                f"references table `{table}` that no migration creates "
                f"(statement: {caption})"))
        else:
            known_tables.append(table)
    known_cols = {"rowid"}
    for table in known_tables:
        known_cols.update(model.tables[table].columns)
    insert_target = parsed["tables"][0] \
        if parsed["head"] == "INSERT" else ""
    for col, _pos in parsed["columns"]:
        if col not in known_cols and col not in parsed["as_aliases"]:
            findings.append(Finding(
                "KO-S001", rel, st["line"],
                f"references column `{col}` that exists in none of "
                f"{', '.join(parsed['tables'])} (statement: {caption})"))
    for qualifier, col in parsed["qualified"]:
        if qualifier == "excluded":
            table = insert_target
        elif qualifier in parsed["alias_map"]:
            table = parsed["alias_map"][qualifier]
        elif qualifier in model.tables:
            table = qualifier
        else:
            continue
        if table in model.tables and col != "rowid" and \
                not model.has_column(table, col):
            findings.append(Finding(
                "KO-S001", rel, st["line"],
                f"references column `{table}.{col}` that no migration "
                f"creates (statement: {caption})"))
    return findings


def _check_repo_class_mirror(model: SchemaModel, rel: str, rc: dict) -> list:
    findings = []
    table = rc["table"]
    if table not in model.tables:
        findings.append(Finding(
            "KO-S001", rel, rc["line"],
            f"repo class {rc['class']} maps table `{table}` that no "
            f"migration creates"))
        return findings
    if rc["columns"] is None:
        return findings
    for col in tuple(rc["columns"]) + _ENTITY_BASE_COLUMNS:
        if not model.has_column(table, col):
            findings.append(Finding(
                "KO-S001", rel, rc["line"],
                f"repo class {rc['class']} mirrors column `{col}` that "
                f"table `{table}` does not declare in any migration"))
    return findings


# ------------------------------------------------------------------ KO-S002 --
def _dialect_findings(literal: str, rel: str, line: int,
                      pragma_sanctioned: bool) -> list:
    findings = []
    masked = mask_strings(literal)
    for pattern, message in _DIALECT_PATTERNS:
        if not pattern.search(masked):
            continue
        if pragma_sanctioned and message.startswith("PRAGMA"):
            continue
        findings.append(Finding("KO-S002", rel, line, message))
    return findings


# ------------------------------------------------------------------ KO-S003 --
def _predicate_columns(parsed: dict) -> set:
    """Positive filter/range predicate columns in a resolved statement.

    SET-clause assignments are masked out (an UPDATE's `col = ?` writes,
    not filters), `NOT IN` is negative, and a predicate whose innermost
    paren group contains OR can't be served by one index probe — skip it
    rather than demand an index that wouldn't be used."""
    masked = parsed["masked"]
    # mask SET ... (up to WHERE) — both UPDATE and DO UPDATE SET forms
    def _blank(m: re.Match) -> str:
        return " " * (m.end() - m.start())
    masked = re.sub(r"\bSET\b.*?(?=\bWHERE\b|$)", _blank, masked,
                    flags=re.IGNORECASE | re.DOTALL)
    # innermost paren span for every position, for the OR-group test
    spans: dict = {}
    stack: list = []
    for i, ch in enumerate(masked):
        if ch == "(":
            stack.append(i)
        elif ch == ")" and stack:
            start = stack.pop()
            for j in range(start, i + 1):
                spans.setdefault(j, (start, i))

    def in_or_group(pos: int) -> bool:
        span = spans.get(pos)
        if span is None:
            return False
        return bool(re.search(r"\bOR\b", masked[span[0]:span[1]],
                              re.IGNORECASE))

    cols = set()
    for m in _PREDICATE_RE.finditer(masked):
        word = m.group(1)
        if word.lower() in ("where", "and", "or", "on", "when", "then",
                            "set", "values"):
            continue
        if not in_or_group(m.start(1)):
            cols.add(word)
    for m in _IN_LIKE_RE.finditer(masked):
        if m.group(1):                      # NOT col IN — negative
            continue
        word = m.group(2)
        if word.upper() == "NOT":           # col NOT IN — negative
            continue
        if word.lower() in ("where", "and", "or"):
            continue
        if not in_or_group(m.start(2)):
            cols.add(word)
    return cols


def _check_index_coverage(model: SchemaModel, rel: str, st: dict) -> list:
    parsed = parse_statement(st["text"])
    if parsed["head"] not in ("SELECT", "DELETE", "UPDATE") \
            or not parsed["tables"]:
        return []
    hot = [t for t in parsed["tables"]
           if t in HOT_TABLES and t in model.tables]
    if not hot:
        return []
    hot_columns = set()
    for table in hot:
        hot_columns.update(model.tables[table].columns)
    predicates = _predicate_columns(parsed)
    if "rowid" in predicates:
        return []       # cursor reads ride the ROWID_SQL stream contract
    predicates &= hot_columns
    if not predicates:
        return []       # full-table aggregation by design (counts, prune)
    leading = {idx.columns[0]
               for table in hot for idx in model.table_indexes(table)}
    if predicates & leading:
        return []
    return [Finding(
        "KO-S003", rel, st["line"],
        f"hot-table query filters on {', '.join(sorted(predicates))} but "
        f"no index on {', '.join(hot)} leads with any of them — add a "
        f"migration index (statement: {_statement_caption(st)})")]


# ------------------------------------------------------------------ driver --
def check_sql_rules(index, root: str, selected=None) -> list:
    """Run the selected KO-S rules over the migration fold + the python
    statement corpus carried by the per-file fact index."""
    selected = set(SQL_RULES) if selected is None else \
        set(selected) & set(SQL_RULES)
    if not selected:
        return []
    migrations_dir = _migrations_dir(root)
    model, problems = build_schema_model(migrations_dir)
    findings: list = []

    if "KO-S004" in selected:
        for fname, line, message in problems:
            findings.append(Finding("KO-S004", _migration_rel(root, fname),
                                    line, message))

    if "KO-S002" in selected and os.path.isdir(migrations_dir):
        # migrations are DDL the Postgres backend replays verbatim — the
        # dialect rule holds them to the same ANSI-ish bar as statements
        for _version, fname, raw, line in \
                iter_migration_statements(migrations_dir):
            findings.extend(_dialect_findings(
                strip_sql_comments(raw), _migration_rel(root, fname), line,
                pragma_sanctioned=False))

    for rel in sorted(index.files):
        sql = getattr(index.files[rel], "sql", None) or {}
        posix_rel = rel.replace(os.sep, "/")
        pragma_ok = posix_rel.endswith(_PRAGMA_SEAM_SUFFIX)
        for st in sql.get("statements", ()):
            if "KO-S002" in selected:
                findings.extend(_dialect_findings(
                    st["literal"], rel, st["line"], pragma_ok))
            if st["dynamic"]:
                continue
            if "KO-S001" in selected:
                findings.extend(
                    _check_conformance_statement(model, rel, st))
            if "KO-S003" in selected:
                findings.extend(_check_index_coverage(model, rel, st))
        if "KO-S001" in selected:
            for rc in sql.get("classes", ()):
                findings.extend(_check_repo_class_mirror(model, rel, rc))
    return findings
