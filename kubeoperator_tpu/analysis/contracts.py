"""Contract rules over the project index: the code-side agreements that
grew in PRs 2-3 and that no per-file syntactic pass can see.

* KO-X009 (config-key contract): the `utils/config.py DEFAULTS` tree is
  the single declaration of the process config surface. Three directions
  must agree: every literal `config.get("a.b.c")` in the package resolves
  in DEFAULTS (a typo'd key silently reads its fallback forever); every
  DEFAULTS leaf is read somewhere (a dead key documents a knob that does
  nothing); every dotted key a docs knob table names exists in DEFAULTS,
  and the resilience/chaos/watchdog blocks are fully documented.

* KO-X010 (surface parity): the platform deliberately ships parallel
  surfaces — REST routes in api/server.py, the koctl CLI's REST calls,
  and koctl --local's in-process dispatch. Every koctl call must resolve
  to a registered route AND a local dispatch case (same commands, two
  transports), every local dispatch case must shadow a real route, and
  every top-level koctl command must be documented.

Both rules take injectable parameters so tests can aim them at fixture
indexes without touching the installed package's contracts.
"""

from __future__ import annotations

import os
import re

from kubeoperator_tpu.analysis.index import ProjectIndex
from kubeoperator_tpu.analysis.report import Finding

# -------------------------------------------------------------- KO-X009 ----
_DOC_KEY_RE = re.compile(r"`([a-z0-9_]+(?:\.[a-z0-9_]+)+)`")
_PURE_KEY_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")

# the config blocks the docs knob tables must cover completely (the
# resilience layer's contract, extended to the observability, fleet,
# scheduler, lease, workloads, slicepool and checkpoint blocks —
# docs/resilience.md + docs/observability.md + docs/scheduler.md +
# docs/workloads.md)
DOC_REQUIRED_SECTIONS = ("resilience", "chaos", "watchdog", "observability",
                         "fleet", "scheduler", "lease", "workloads",
                         "slicepool", "checkpoint", "queue", "converge",
                         "serve")


def _defaults_from_tree(root: str) -> dict | None:
    """The DEFAULTS literal parsed out of the ANALYZED tree's
    utils/config.py (pure-literal dict, so ast.literal_eval suffices).
    None when it can't be read — the caller falls back to the installed
    package's import, which is identical for the default root."""
    import ast

    path = os.path.join(root, "utils", "config.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            if any(isinstance(t, ast.Name) and t.id == "DEFAULTS"
                   for t in targets):
                value = ast.literal_eval(node.value)
                return value if isinstance(value, dict) else None
    except (OSError, SyntaxError, ValueError):
        pass
    return None


def _flatten(tree: dict, prefix: str = "") -> set:
    """Leaf keys of a nested dict as dotted paths."""
    out: set = set()
    for key, value in tree.items():
        dotted = f"{prefix}{key}"
        if isinstance(value, dict) and value:
            out |= _flatten(value, dotted + ".")
        else:
            out.add(dotted)
    return out


def _resolves(key: str, defaults: dict) -> bool:
    """A read may target a leaf OR an interior mapping node."""
    node = defaults
    for part in key.split("."):
        if not isinstance(node, dict) or part not in node:
            return False
        node = node[part]
    return True


def _doc_table_keys(docs_dir: str) -> list:
    """[(key, file, line)] for every pure-dotted backticked key inside a
    markdown KNOB table (a table whose header row mentions 'default') —
    the scoping that keeps prose like `db.statement_is_complete` from
    reading as a config key."""
    out: list = []
    if not os.path.isdir(docs_dir):
        return out
    for fn in sorted(os.listdir(docs_dir)):
        if not fn.endswith(".md"):
            continue
        path = os.path.join(docs_dir, fn)
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
        in_knob_table = False
        for i, line in enumerate(lines, start=1):
            stripped = line.strip()
            if not stripped.startswith("|"):
                in_knob_table = False
                continue
            if set(stripped) <= {"|", "-", ":", " "}:
                continue   # the separator row
            is_header = i < len(lines) and \
                set(lines[i].strip()) <= {"|", "-", ":", " "} and \
                lines[i].strip().startswith("|")
            if is_header:
                # a knob table is one whose HEADER row says "default" —
                # body rows that merely contain the word (KO-P004's
                # "mutable default") must not arm the scan
                in_knob_table = "default" in stripped.lower()
                continue
            if not in_knob_table:
                continue
            for match in _DOC_KEY_RE.finditer(stripped):
                key = match.group(1)
                if _PURE_KEY_RE.match(key):
                    out.append((key, os.path.join("docs", fn), i))
    return out


def check_config_contract(
    index: ProjectIndex,
    defaults: dict | None = None,
    docs_dir: str | None = None,
    doc_required_sections: tuple = DOC_REQUIRED_SECTIONS,
) -> list:
    """KO-X009 — see the module docstring."""
    if defaults is None:
        # live mode: the analyzed tree's own config surface. A fixture /
        # --root tree that ships no utils/config.py has no config surface
        # to check — skip rather than drown it in dead-key findings for
        # knobs it never declared. When the tree HAS one, its DEFAULTS
        # literal is read from THAT file (a --root checkout is checked
        # against its own declarations, not the installed analyzer's).
        if not any(rel.replace(os.sep, "/").endswith("utils/config.py")
                   for rel in index.files):
            return []
        defaults = _defaults_from_tree(index.root)
        if defaults is None:
            from kubeoperator_tpu.utils.config import DEFAULTS as defaults
    if docs_dir is None:
        docs_dir = os.path.join(os.path.dirname(index.root), "docs")

    findings: list = []
    leaves = _flatten(defaults)
    reads = index.config_reads()
    read_keys = {key for key, _rel, _line in reads}

    # 1) every read resolves in DEFAULTS
    for key, rel, line in reads:
        if not _resolves(key, defaults):
            findings.append(Finding(
                "KO-X009", rel, line,
                f"config key {key!r} is read but not declared in "
                f"utils/config.py DEFAULTS — a typo here silently reads "
                f"the call-site fallback forever; declare the key (with "
                f"its default) or fix the spelling",
            ))

    # 2) every DEFAULTS leaf is read somewhere (dead-knob detector).
    # A read of an interior node (`config.section`-style dotted prefix)
    # covers all leaves under it.
    config_rel = ""
    for rel in index.files:
        if rel.replace(os.sep, "/").endswith("utils/config.py"):
            config_rel = rel
    for leaf in sorted(leaves):
        covered = leaf in read_keys or any(
            leaf.startswith(key + ".") for key in read_keys)
        if not covered:
            findings.append(Finding(
                "KO-X009", config_rel or "utils/config.py", 0,
                f"DEFAULTS key {leaf!r} is never read by any "
                f"config.get() — a knob that does nothing; wire it or "
                f"delete it",
            ))

    # 3) docs knob tables agree with DEFAULTS
    doc_keys = _doc_table_keys(docs_dir)
    for key, rel, line in doc_keys:
        if not _resolves(key, defaults):
            findings.append(Finding(
                "KO-X009", rel, line,
                f"docs knob table names {key!r} which does not exist in "
                f"utils/config.py DEFAULTS — stale or typo'd documentation",
            ))
    documented = {key for key, _rel, _line in doc_keys}
    for section in doc_required_sections:
        for leaf in sorted(leaves):
            if leaf.split(".")[0] == section and leaf not in documented:
                findings.append(Finding(
                    "KO-X009", config_rel or "utils/config.py", 0,
                    f"{leaf!r} ({section}.* block) has no row in any docs "
                    f"knob table — the resilience-layer contract requires "
                    f"every knob documented (docs/resilience.md)",
                ))
    return findings


# -------------------------------------------------------------- KO-X010 ----
def _template_match(a: str, b: str) -> bool:
    """Segment-wise route template equality; any {placeholder} matches any
    other {placeholder}."""
    sa, sb = a.strip("/").split("/"), b.strip("/").split("/")
    if len(sa) != len(sb):
        return False
    for x, y in zip(sa, sb):
        if x.startswith("{") and y.startswith("{"):
            continue
        if x != y:
            return False
    return True


def _matches_any(method: str, template: str, surface: list) -> bool:
    return any(m == method and _template_match(template, t)
               for m, t, _line, _rel in surface)


def check_surface_parity(
    index: ProjectIndex,
    docs_text: str | None = None,
) -> list:
    """KO-X010 — see the module docstring. `docs_text` is the concatenated
    documentation corpus (README + docs/*.md); None loads it from the
    tree next to the analysis root."""
    findings: list = []
    routes = index.surface("routes")
    rest_calls = index.surface("rest_calls")
    dispatch = index.surface("dispatch")
    commands = index.surface("commands")

    # 1) every koctl REST call resolves to a registered server route
    if routes:
        for method, template, line, rel in rest_calls:
            if not _matches_any(method, template, routes):
                findings.append(Finding(
                    "KO-X010", rel, line,
                    f"CLI calls {method} {template} but api/server.py "
                    f"registers no matching route — the REST transport "
                    f"404s where --local might work",
                ))

    # 2) every koctl REST call has a --local dispatch case (two
    # transports, same commands)
    if dispatch:
        for method, template, line, rel in rest_calls:
            if not _matches_any(method, template, dispatch):
                findings.append(Finding(
                    "KO-X010", rel, line,
                    f"CLI calls {method} {template} but LocalClient."
                    f"_dispatch has no matching case — `--local` dies "
                    f"with 'no route' on a command REST serves",
                ))

    # 3) every --local dispatch case shadows a real REST route (a
    # local-only verb means the REST surface silently lagged)
    if routes:
        for method, template, line, rel in dispatch:
            if not _matches_any(method, template, routes):
                findings.append(Finding(
                    "KO-X010", rel, line,
                    f"LocalClient dispatches {method} {template} but "
                    f"api/server.py registers no such route — the local "
                    f"transport grew a verb REST does not serve",
                ))

    # 4) every top-level koctl command is documented
    if commands:
        if docs_text is None:
            parent = os.path.dirname(index.root)
            chunks: list = []
            for candidate in [os.path.join(parent, "README.md")]:
                if os.path.exists(candidate):
                    with open(candidate, encoding="utf-8") as f:
                        chunks.append(f.read())
            docs_dir = os.path.join(parent, "docs")
            if os.path.isdir(docs_dir):
                for fn in sorted(os.listdir(docs_dir)):
                    if fn.endswith(".md"):
                        with open(os.path.join(docs_dir, fn),
                                  encoding="utf-8") as f:
                            chunks.append(f.read())
            docs_text = "\n".join(chunks)
        for name, line, rel in commands:
            if f"koctl {name}" not in docs_text:
                findings.append(Finding(
                    "KO-X010", rel, line,
                    f"koctl subcommand {name!r} appears in no "
                    f"documentation (README.md / docs/*.md must mention "
                    f"`koctl {name}`) — undocumented operator surface",
                ))
    return findings
