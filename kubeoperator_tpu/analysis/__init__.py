"""ko-analyze — static analysis over the platform's artifacts and code.

Two engines, one report:

* `artifacts` — cross-artifact linter resolving every reference between
  playbooks, roles, templates, the offline bundle contract, SQL
  migrations, and TPU plan topology (rules KO-X001..KO-X008).
* `astcheck` — project-rule AST checker over the python package itself
  (rules KO-P001..KO-P005: repository layering, non-blocking handlers,
  lock discipline, mutable defaults, bare excepts).

`run_analysis()` is the single entry point `koctl lint`, the
`/api/v1/analysis` endpoint, and the tier-1 static gate
(tests/test_static_gate.py) all share. docs/analysis.md documents every
rule id and how to add one.
"""

from __future__ import annotations

import os
import time

from kubeoperator_tpu.analysis.artifacts import ARTIFACT_RULES, AnalysisContext
from kubeoperator_tpu.analysis.astcheck import AST_RULES, run_ast_rules
from kubeoperator_tpu.analysis.report import (
    ERROR,
    RULES,
    WARNING,
    Finding,
    Report,
    RuleSpec,
)

__all__ = [
    "ERROR", "WARNING", "Finding", "Report", "RuleSpec", "RULES",
    "default_root", "run_analysis",
]


def default_root() -> str:
    """The installed package dir — content/, repository/migrations/ and all
    platform python live under it, so it IS the analysis universe."""
    return os.path.dirname(os.path.abspath(__file__)).rsplit(os.sep, 1)[0]


def run_analysis(root: str | None = None, plan_files=(),
                 rule_ids=None) -> Report:
    """Run the selected rules (default: all registered) over `root`.

    Internal analyzer failures propagate as exceptions — the CLI maps them
    to exit code 2; a gate must never mistake a crashed analyzer for a
    clean tree.
    """
    root = os.path.abspath(root or default_root())
    start = time.perf_counter()
    ctx = AnalysisContext(root=root, plan_files=tuple(plan_files))
    report = Report(root=root)
    for rule_id, rule_fn in ARTIFACT_RULES.items():
        if rule_ids is not None and rule_id not in rule_ids:
            continue
        report.extend(rule_fn(ctx))
        report.rules_run.append(rule_id)
    ast_selected = [
        rid for rid in AST_RULES if rule_ids is None or rid in rule_ids
    ]
    if ast_selected:
        findings, scanned = run_ast_rules(root, set(ast_selected))
        report.extend(findings)
        report.rules_run.extend(ast_selected)
        report.files_scanned += scanned
    report.files_scanned += ctx.files_scanned
    report.runtime_s = time.perf_counter() - start
    return report
