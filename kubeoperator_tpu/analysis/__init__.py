"""ko-analyze — static analysis over the platform's artifacts and code.

The v2 engine, four rule layers over one report:

* `artifacts` — cross-artifact linter resolving every reference between
  playbooks, roles, templates, the offline bundle contract, SQL
  migrations, and TPU plan topology (KO-X001..KO-X008).
* `astcheck` — per-file project AST rules (KO-P001..KO-P007; KO-P003
  retired in favour of KO-P008).
* `flow` — project-wide dataflow rules over the symbol index: guarded-by
  inference (KO-P008) and exception-flow discipline (KO-P009).
* `contracts` — cross-layer contract rules over the same index: the
  config-key contract (KO-X009) and REST/CLI surface parity (KO-X010).

`index.py` is the substrate: each package python file is parsed once per
run, reduced to serializable facts, and cached by content hash so a warm
`koctl lint` re-parses only what changed — that is how the gate stays
inside its 5 s budget as rules multiply. `sarif.py` adds SARIF 2.1.0
output and the checked-in waiver/baseline file.

`run_analysis()` is the single entry point `koctl lint`, the
`/api/v1/analysis` endpoint, and the tier-1 static gate
(tests/test_static_gate.py) all share. docs/analysis.md documents every
rule id and how to add one.
"""

from __future__ import annotations

import ast
import os
import time

from kubeoperator_tpu.analysis.artifacts import ARTIFACT_RULES, AnalysisContext
from kubeoperator_tpu.analysis.astcheck import AST_RULES
from kubeoperator_tpu.analysis.contracts import (
    check_config_contract,
    check_surface_parity,
)
from kubeoperator_tpu.analysis.flow import (
    check_exception_flow,
    check_guarded_by,
    check_span_discipline,
)
from kubeoperator_tpu.analysis.index import (
    AnalysisCache,
    FileFacts,
    ProjectIndex,
    extract_file_facts,
    file_sha,
    iter_python_files,
    tree_sha,
)
from kubeoperator_tpu.analysis.report import (
    ERROR,
    RULES,
    WARNING,
    Finding,
    Report,
    RuleSpec,
)
from kubeoperator_tpu.analysis.sarif import (
    apply_waivers,
    load_waivers,
    to_sarif,
    to_sarif_json,
)
from kubeoperator_tpu.analysis.sqlrules import SQL_RULES, check_sql_rules

__all__ = [
    "ERROR", "WARNING", "Finding", "Report", "RuleSpec", "RULES",
    "default_root", "run_analysis", "to_sarif", "to_sarif_json",
]

# project-wide rules that consume the index rather than one file's tree
FLOW_PROJECT_RULES = ("KO-P008",)
CONTRACT_RULES = ("KO-X009", "KO-X010")
# SQL rules (sqlrules.py SQL_RULES) run fresh every run over the cached
# per-file facts + the migration fold — so `--changed` naturally re-checks
# SQL when a .sql file changes (migrations are never behind the fast path)
# per-file flow rules cached alongside the astcheck per-file rules
PER_FILE_FLOW_RULES = ("KO-P009", "KO-P010")


def default_root() -> str:
    """The installed package dir — content/, repository/migrations/ and all
    platform python live under it, so it IS the analysis universe."""
    return os.path.dirname(os.path.abspath(__file__)).rsplit(os.sep, 1)[0]


def default_waivers_path(root: str) -> str:
    return os.path.join(root, "analysis", "waivers.yaml")


def _run_artifact_rules(report: Report, root: str, plan_files: tuple,
                        selected: set, cache: AnalysisCache | None,
                        changed: set | None = None,
                        git_head: str = "") -> None:
    chosen = [rid for rid in ARTIFACT_RULES if rid in selected]
    if not chosen:
        return
    full_set = len(chosen) == len(ARTIFACT_RULES)
    entry = None
    t_sha = ""
    if cache is not None and full_set:
        # --changed fast path around the whole-tree hash, taken only when
        # the cache's recorded git state can vouch for it (same HEAD,
        # clean-at-save, clean-now, no plan files then or now)
        if changed is not None and not plan_files:
            entry = cache.artifact_fast_entry(git_head, changed, root)
        if entry is None:
            t_sha = tree_sha(root)
            for pf in plan_files:
                t_sha += file_sha(pf) if os.path.exists(pf) else "<missing>"
            entry = cache.artifact_lookup(t_sha)
    if entry is not None:
        for rid in chosen:
            report.extend([Finding.from_dict(d)
                           for d in entry["findings"].get(rid, [])])
            report.rules_run.append(rid)
        report.files_scanned += entry.get("files_scanned", 0)
        report.cache_hits += 1
        return
    ctx = AnalysisContext(root=root, plan_files=tuple(plan_files))
    by_rule: dict = {}
    for rid in chosen:
        findings = ARTIFACT_RULES[rid](ctx)
        by_rule[rid] = [f.to_dict() for f in findings]
        report.extend(findings)
        report.rules_run.append(rid)
    report.files_scanned += ctx.files_scanned
    if cache is not None and full_set:
        cache.artifact_store(t_sha, by_rule, ctx.files_scanned,
                             plans=plan_files)


def _per_file_rules(selected: set) -> dict:
    """rule id -> (root, tree, path, source) -> findings, for every
    selected per-file rule (astcheck + per-file flow)."""
    rules: dict = {}
    for rid, fn in AST_RULES.items():
        if rid in selected:
            rules[rid] = (lambda root, tree, path, source, _fn=fn:
                          _fn(root, tree, path))
    if "KO-P009" in selected:
        rules["KO-P009"] = (
            lambda root, tree, path, source:
            check_exception_flow(root, tree, path, source))
    if "KO-P010" in selected:
        rules["KO-P010"] = (
            lambda root, tree, path, source:
            check_span_discipline(root, tree, path, source))
    return rules


def _run_python_rules(report: Report, root: str, selected: set,
                      cache: AnalysisCache | None,
                      changed: set | None) -> ProjectIndex:
    """One walk serves the per-file rules AND builds the project index.
    A syntactically broken file raises — the gate must hard-fail (exit 2),
    not report it as a lint finding a --format json consumer might filter
    away."""
    per_file = _per_file_rules(selected)
    index = ProjectIndex(root=root)
    parent = os.path.dirname(root) or "."
    live_rels: set = set()
    for path in iter_python_files(root):
        rel = os.path.relpath(path, parent)
        live_rels.add(rel)
        report.files_scanned += 1
        entry = None
        if cache is not None:
            entry = cache.lookup(rel, file_sha(path))
            if entry is not None and \
                    not set(per_file) <= set(entry["findings"]):
                entry = None    # cached run covered fewer rules
        if entry is not None:
            index.files[rel] = FileFacts.from_dict(entry["facts"])
            for rid in per_file:
                report.extend([Finding.from_dict(d)
                               for d in entry["findings"][rid]])
            continue
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        facts = extract_file_facts(tree, rel)
        index.files[rel] = facts
        findings_by_rule: dict = {}
        for rid, fn in per_file.items():
            findings = fn(root, tree, path, source)
            findings_by_rule[rid] = [f.to_dict() for f in findings]
            report.extend(findings)
        if cache is not None:
            cache.store(rel, file_sha(path), facts, findings_by_rule)
    if cache is not None and changed is None:
        cache.prune(live_rels)
    report.rules_run.extend(sorted(per_file))
    return index


def run_analysis(root: str | None = None, plan_files=(),
                 rule_ids=None, *, cache_dir: str | None = None,
                 changed: set | None = None, git_head: str = "",
                 waivers_path: str | None = None) -> Report:
    """Run the selected rules (default: all registered) over `root`.

    `cache_dir` enables the content-hash incremental cache (koctl lint
    passes its default; the tier-1 gate runs cold on purpose so the
    recorded budget stays honest). Every python file is always verified
    by content hash — cheap, and 'git status clean' cannot prove cache
    freshness. `changed` + `git_head` (`koctl lint --changed`) let the
    cache skip the whole-tree artifact hash when the recorded git state
    vouches for it. Waivers load from `analysis/waivers.yaml` under the
    root unless overridden.

    Internal analyzer failures propagate as exceptions — the CLI maps them
    to exit code 2; a gate must never mistake a crashed analyzer for a
    clean tree.
    """
    root = os.path.abspath(root or default_root())
    selected = set(RULES) if rule_ids is None else set(rule_ids)
    start = time.perf_counter()
    cache = AnalysisCache(cache_dir, root) if cache_dir else None
    report = Report(root=root)

    _run_artifact_rules(report, root, tuple(plan_files), selected, cache,
                        changed, git_head)
    index = _run_python_rules(report, root, selected, cache, changed)

    if "KO-P008" in selected:
        report.extend(check_guarded_by(index))
        report.rules_run.append("KO-P008")
    if "KO-X009" in selected:
        report.extend(check_config_contract(index))
        report.rules_run.append("KO-X009")
    if "KO-X010" in selected:
        report.extend(check_surface_parity(index))
        report.rules_run.append("KO-X010")
    if selected & set(SQL_RULES):
        report.extend(check_sql_rules(index, root, selected))
        report.rules_run.extend(sorted(selected & set(SQL_RULES)))

    waivers = load_waivers(waivers_path or default_waivers_path(root))
    report.findings, unused = apply_waivers(report.findings, waivers)
    # a waiver is stale only if the rule it baselines actually RAN and
    # still produced nothing it matches — a --rules subset must not flag
    # every other rule's waivers
    report.unused_waivers = [
        f"{w.rule} file={w.file or '*'} contains={w.contains or '*'}"
        for w in unused if w.rule in selected
    ]

    if cache is not None:
        report.cache_hits += cache.hits
        report.cache_misses += cache.misses
        cache.record_git_state(
            git_head if changed is not None else "", changed or set(), root)
        cache.save()
    report.runtime_s = time.perf_counter() - start
    return report
