"""Project-rule AST checker over the python package itself.

These are not style rules — each encodes a correctness invariant this
codebase relies on and has been bitten by elsewhere: the repository layer
owns the database handle, request handlers never block the event loop,
child processes are deadlined, and in-flight phase flips ride the journal.
(Lock discipline moved to the project-wide guarded-by engine, KO-P008 in
flow.py; exception-flow discipline is KO-P009 there too.)

Every rule is a pure function (root, tree, path) -> list[Finding]; the
scanner parses each file once and hands the same tree to all selected
rules — in the v2 engine that shared parse happens in run_analysis's
index walk (analysis/index.py), with run_ast_rules kept as the
fixture-test entry point.
"""

from __future__ import annotations

import ast
import os

from kubeoperator_tpu.analysis.report import Finding

# dirs under the package root that are not platform python code: content/
# carries node-side payload scripts, __pycache__ is noise
_SKIP_DIRS = {"content", "__pycache__"}


def iter_python_files(root: str):
    for base, dirs, files in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
        for fn in sorted(files):
            if fn.endswith(".py"):
                yield os.path.join(base, fn)


def _rel(root: str, path: str) -> str:
    return os.path.relpath(path, os.path.dirname(root) or ".")


# ---------------------------------------------------------------- KO-P001 ---
def check_repo_layering(root: str, tree: ast.AST, path: str) -> list:
    """sqlite3 may be touched only under repository/ — every other layer
    goes through Repositories, so schema, locking, and transaction scope
    stay in one place."""
    rel = _rel(root, path)
    if "repository" in os.path.relpath(path, root).split(os.sep)[:-1]:
        return []
    findings: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            names = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom):
            names = [node.module or ""]
        else:
            continue
        for name in names:
            if name == "sqlite3" or name.startswith("sqlite3."):
                findings.append(Finding(
                    "KO-P001", rel, node.lineno,
                    "sqlite3 imported outside the repository layer — DB "
                    "access goes through kubeoperator_tpu.repository",
                ))
    return findings


# ---------------------------------------------------------------- KO-P002 ---
_BLOCKING_CALLS = {
    ("time", frozenset({"sleep"})),
    ("_time", frozenset({"sleep"})),
    ("subprocess", frozenset({
        "run", "call", "check_call", "check_output", "Popen",
    })),
    ("requests", frozenset({
        "get", "post", "put", "delete", "head", "request", "Session",
    })),
    ("_requests", frozenset({
        "get", "post", "put", "delete", "head", "request",
    })),
    ("os", frozenset({"system"})),
}


def _blocking_call_name(call: ast.Call) -> str | None:
    func = call.func
    if not isinstance(func, ast.Attribute) or not isinstance(func.value, ast.Name):
        return None
    for base, attrs in _BLOCKING_CALLS:
        if func.value.id == base and func.attr in attrs:
            return f"{func.value.id}.{func.attr}"
    return None


class _AsyncBodyScanner(ast.NodeVisitor):
    """Walk an async function's own body, NOT descending into nested
    function defs: a sync closure defined inside a handler is the run_sync
    off-load idiom (it executes on a worker thread), and nested async defs
    get their own top-level visit."""

    def __init__(self) -> None:
        self.calls: list = []

    def visit_FunctionDef(self, node):  # noqa: N802 — do not descend
        pass

    def visit_AsyncFunctionDef(self, node):  # noqa: N802 — own visit
        pass

    def visit_Call(self, node):  # noqa: N802
        name = _blocking_call_name(node)
        if name:
            self.calls.append((node.lineno, name))
        self.generic_visit(node)


def check_blocking_handlers(root: str, tree: ast.AST, path: str) -> list:
    findings: list = []
    rel = _rel(root, path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.AsyncFunctionDef):
            continue
        scanner = _AsyncBodyScanner()
        for stmt in node.body:
            scanner.visit(stmt)
        for lineno, name in scanner.calls:
            findings.append(Finding(
                "KO-P002", rel, lineno,
                f"blocking {name}() inside async {node.name}() — this "
                f"stalls the event loop; off-load via run_sync/to_thread",
            ))
    return findings


# KO-P003 (single-file lock-discipline) retired: superseded by the
# project-wide guarded-by inference KO-P008 in flow.py, which propagates
# lock-held context through self-calls and inheritance instead of only
# reading the lexical `with` nesting of one method at a time.


# ---------------------------------------------------------------- KO-P004 ---
def _is_mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    return (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in {"list", "dict", "set", "bytearray"}
            and not node.args and not node.keywords)


def check_mutable_defaults(root: str, tree: ast.AST, path: str) -> list:
    findings: list = []
    rel = _rel(root, path)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            continue
        defaults = list(node.args.defaults) + [
            d for d in node.args.kw_defaults if d is not None
        ]
        for default in defaults:
            if _is_mutable_default(default):
                name = getattr(node, "name", "<lambda>")
                findings.append(Finding(
                    "KO-P004", rel, default.lineno,
                    f"mutable default argument on {name}() — one shared "
                    f"instance aliases across every call",
                ))
    return findings


# ---------------------------------------------------------------- KO-P005 ---
def check_bare_except(root: str, tree: ast.AST, path: str) -> list:
    findings: list = []
    rel = _rel(root, path)
    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            findings.append(Finding(
                "KO-P005", rel, node.lineno,
                "bare except: swallows KeyboardInterrupt/SystemExit — "
                "catch Exception or narrower",
            ))
    return findings


# ---------------------------------------------------------------- KO-P006 ---
_SUBPROCESS_FNS = frozenset({
    "run", "Popen", "call", "check_call", "check_output",
})
_P006_WAIVER = "KO-P006: waived"


def check_subprocess_timeouts(root: str, tree: ast.AST, path: str) -> list:
    """Every subprocess.run/Popen/check_* call outside terminal/ must pass
    a timeout= — an un-deadlined child process is exactly how a hung
    external binary wedges a deploy forever (the resilience layer's
    cooperative-cancel contract assumes every blocking child is bounded).

    terminal/ is exempt wholesale: interactive shells live as long as the
    user does. Elsewhere a call that genuinely cannot take timeout=
    (Popen with its own kill hook) is waived with a `# KO-P006: waived —
    <reason>` comment on the call line or the line above it."""
    parts = os.path.relpath(path, root).split(os.sep)[:-1]
    if "terminal" in parts:
        return []
    candidates: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) or \
                not isinstance(func.value, ast.Name):
            continue
        if func.value.id not in ("subprocess", "_subprocess") or \
                func.attr not in _SUBPROCESS_FNS:
            continue
        if any(kw.arg == "timeout" for kw in node.keywords):
            continue
        candidates.append(node)
    if not candidates:
        return []
    with open(path, encoding="utf-8") as f:
        source_lines = f.read().splitlines()

    def waived(lineno: int) -> bool:
        # waiver comment on the call line or on any of the 3 lines above
        # (multi-line call heads push the comment up)
        lo = max(lineno - 4, 0)
        return any(
            _P006_WAIVER in line for line in source_lines[lo:lineno]
        )

    findings: list = []
    rel = _rel(root, path)
    for node in candidates:
        if waived(node.lineno):
            continue
        findings.append(Finding(
            "KO-P006", rel, node.lineno,
            f"subprocess.{node.func.attr}() without timeout= — a hung "
            f"child wedges its caller forever; pass timeout= or waive "
            f"with `# {_P006_WAIVER} — <reason>`",
        ))
    return findings


# ---------------------------------------------------------------- KO-P007 ---
# the phases that mean "a controller owns this cluster" — kept in sync with
# resilience/journal.py IN_FLIGHT_PHASES (enum NAMES here, VALUES below,
# so both the `ClusterPhaseStatus.X[.value]` and string-literal spellings
# of an in-flight write are caught)
_INFLIGHT_NAMES = frozenset({
    "PROVISIONING", "DEPLOYING", "SCALING", "UPGRADING", "TERMINATING",
})
_INFLIGHT_VALUES = frozenset({
    "Provisioning", "Deploying", "Scaling", "Upgrading", "Terminating",
})
# the sanctioned writers: the phase engine and the journal helper
_P007_ALLOWED_DIRS = ("adm",)
_P007_ALLOWED_FILES = frozenset({os.path.join("resilience", "journal.py")})


def _mentions_inflight_phase(value: ast.AST) -> str | None:
    """The in-flight phase an expression names, if any: matches
    `ClusterPhaseStatus.DEPLOYING` (with or without `.value`) and the bare
    string literal "Deploying" — the two ways code spells the write."""
    for sub in ast.walk(value):
        if isinstance(sub, ast.Attribute) and sub.attr in _INFLIGHT_NAMES \
                and isinstance(sub.value, ast.Name) \
                and sub.value.id == "ClusterPhaseStatus":
            return sub.attr
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and sub.value in _INFLIGHT_VALUES:
            return sub.value
    return None


def check_phase_write_discipline(root: str, tree: ast.AST, path: str) -> list:
    """In-flight ClusterPhaseStatus writes (`<x>.phase = Deploying/...`)
    are allowed only in adm/ and the operation-journal helper. Everywhere
    else a bare in-flight flip would put a cluster into "a controller owns
    me" without the durable journal record the boot reconciler needs — the
    exact amnesia this repo's crash-safety layer exists to end. Route the
    write through OperationJournal.open/set_phase instead."""
    relpath = os.path.relpath(path, root)
    parts = relpath.split(os.sep)
    if parts[0] in _P007_ALLOWED_DIRS or relpath in _P007_ALLOWED_FILES:
        return []
    findings: list = []
    rel = _rel(root, path)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if not any(isinstance(t, ast.Attribute) and t.attr == "phase"
                   for t in targets):
            continue
        phase = _mentions_inflight_phase(value)
        if phase is None:
            continue
        findings.append(Finding(
            "KO-P007", rel, node.lineno,
            f"in-flight phase {phase!r} assigned outside adm/ and the "
            f"operation journal — a crash here strands the cluster with no "
            f"journal record; use OperationJournal.open/set_phase",
        ))
    return findings


# ---------------------------------------------------------------- KO-P011 ---
_P011_WAIVER = "KO-P011: waived"
# write-capable builtin-open modes; a mode that cannot be proven a write
# (non-constant) is left quiet — the rule must never cry wolf on reads
_P011_WRITE_CHARS = set("wax+")


def _p011_candidates(tree: ast.AST) -> list:
    """(lineno, description) for every durable-write call made OUTSIDE an
    atomic_* helper: builtin open() in a write mode, Path-style
    .write_text/.write_bytes, and file-form json.dump."""
    out: list = []

    def visit(node, in_atomic: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            in_atomic = in_atomic or node.name.lstrip("_") \
                .startswith("atomic_")
        if isinstance(node, ast.Call) and not in_atomic:
            func = node.func
            if isinstance(func, ast.Name) and func.id == "open":
                mode = None
                if len(node.args) > 1:
                    mode = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "mode":
                        mode = kw.value
                if isinstance(mode, ast.Constant) \
                        and isinstance(mode.value, str) \
                        and set(mode.value) & _P011_WRITE_CHARS:
                    out.append((node.lineno,
                                f"open(..., {mode.value!r})"))
            elif isinstance(func, ast.Attribute) \
                    and func.attr in ("write_text", "write_bytes"):
                out.append((node.lineno, f".{func.attr}(...)"))
            elif isinstance(func, ast.Attribute) and func.attr == "dump" \
                    and isinstance(func.value, ast.Name) \
                    and func.value.id == "json":
                out.append((node.lineno, "json.dump(...)"))
        for child in ast.iter_child_nodes(node):
            visit(child, in_atomic)

    visit(tree, False)
    return out


def check_checkpoint_atomic_writes(root: str, tree: ast.AST,
                                   path: str) -> list:
    """Checkpoint-persistence modules (any `checkpoint.py` in the
    package) must route every durable write through the tmp+rename
    helper — a checkpoint's whole value is that a crash mid-save can
    never produce a half-written shard a restore would trust, and one
    bare `open(path, "w")` re-opens exactly that window. Functions named
    `atomic_*`/`_atomic_*` ARE the helper (they own the tmp+`os.replace`
    dance); everything else writes through them or carries a
    `# KO-P011: waived — <reason>` comment."""
    if os.path.basename(path) != "checkpoint.py":
        return []
    candidates = _p011_candidates(tree)
    if not candidates:
        return []
    with open(path, encoding="utf-8") as f:
        source_lines = f.read().splitlines()

    def waived(lineno: int) -> bool:
        lo = max(lineno - 4, 0)
        return any(_P011_WAIVER in line
                   for line in source_lines[lo:lineno])

    rel = _rel(root, path)
    findings: list = []
    for lineno, desc in candidates:
        if waived(lineno):
            continue
        findings.append(Finding(
            "KO-P011", rel, lineno,
            f"{desc} writes checkpoint bytes without the tmp+rename "
            f"helper — a crash mid-write leaves a torn file a restore "
            f"could trust; route through atomic_write_bytes/"
            f"atomic_write_json or waive with `# {_P011_WAIVER} — "
            f"<reason>`",
        ))
    return findings


# ---------------------------------------------------------------- KO-P012 ---
# the one sanctioned event writer: observability/events.py emit_event()
# (the journal's fenced paths and every service route through it)
_P012_ALLOWED_FILES = frozenset({
    os.path.join("observability", "events.py"),
})


def check_event_discipline(root: str, tree: ast.AST, path: str) -> list:
    """Bus-event emission (`<anything>.events.save(...)` /
    `.events.save_many(...)`) happens only inside observability/events.py
    — everywhere else a state-transition writer must route through
    `emit_event` / the journal's event verbs, which is what guarantees
    (a) every event commits in the same transaction as the state change
    it describes and (b) a fenced-out writer cannot narrate state it no
    longer owns. An ad-hoc EventRepo save would silently break both."""
    relpath = os.path.relpath(path, root)
    if relpath in _P012_ALLOWED_FILES:
        return []
    findings: list = []
    rel = _rel(root, path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if not isinstance(func, ast.Attribute) or \
                func.attr not in ("save", "save_many"):
            continue
        target = func.value
        is_events = (
            (isinstance(target, ast.Attribute) and target.attr == "events")
            or (isinstance(target, ast.Name) and target.id == "events")
        )
        if is_events:
            findings.append(Finding(
                "KO-P012", rel, node.lineno,
                "ad-hoc event write outside the emission funnel — route "
                "through observability.events.emit_event (or the "
                "journal's event verbs) so the row commits in the same "
                "transaction as the state change it describes",
            ))
    return findings


# ---------------------------------------------------------------- KO-P013 ---
# vocabulary cache: root -> (kinds frozenset, prefixes tuple); parsing
# observability/events.py once per analyzed tree, not once per file
_P013_VOCAB: dict = {}


def _event_kind_vocabulary(root: str) -> tuple:
    """The EventKind class's string constants parsed out of the ANALYZED
    tree's observability/events.py: (exact kinds, allowed prefixes).
    Names ending `_PREFIX` declare an open dotted family ("slice." —
    slice.detected, slice.drained, ...) rather than one exact kind. A
    tree that ships no events.py falls back to the installed package's
    vocabulary (fixture trees are checked against the real alphabet)."""
    if root in _P013_VOCAB:
        return _P013_VOCAB[root]
    kinds: set = set()
    prefixes: list = []
    path = os.path.join(root, "observability", "events.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        tree = None
    class_node = None
    if tree is not None:
        for node in tree.body:
            if isinstance(node, ast.ClassDef) and node.name == "EventKind":
                class_node = node
    if class_node is not None:
        for stmt in class_node.body:
            if not isinstance(stmt, ast.Assign):
                continue
            value = stmt.value
            if not (isinstance(value, ast.Constant)
                    and isinstance(value.value, str)):
                continue
            for target in stmt.targets:
                if not isinstance(target, ast.Name):
                    continue
                if target.id.endswith("_PREFIX"):
                    prefixes.append(value.value)
                else:
                    kinds.add(value.value)
    else:
        from kubeoperator_tpu.observability.events import EventKind

        for name in vars(EventKind):
            value = getattr(EventKind, name)
            if name.startswith("_") or not isinstance(value, str):
                continue
            if name.endswith("_PREFIX"):
                prefixes.append(value)
            else:
                kinds.add(value)
    vocab = (frozenset(kinds), tuple(prefixes))
    _P013_VOCAB[root] = vocab
    return vocab


def check_event_kind_discipline(root: str, tree: ast.AST,
                                path: str) -> list:
    """Every LITERAL event kind reaching `emit_event(...)` (second
    positional or `kind=`) must resolve in the EventKind vocabulary —
    exactly, or under a declared `*_PREFIX` dotted family. A typo'd kind
    string would stream events no filter, story reducer, or dashboard
    ever selects: silently lost telemetry, which is worse than no
    telemetry. Computed kinds (EventKind attributes, f-strings, variables)
    pass — the vocabulary class is the one place they resolve from."""
    findings: list = []
    rel = _rel(root, path)
    kinds, prefixes = _event_kind_vocabulary(root)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        name = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        if name != "emit_event":
            continue
        kind_arg = None
        if len(node.args) >= 2:
            kind_arg = node.args[1]
        for kw in node.keywords:
            if kw.arg == "kind":
                kind_arg = kw.value
        if not (isinstance(kind_arg, ast.Constant)
                and isinstance(kind_arg.value, str)):
            continue
        kind = kind_arg.value
        if kind in kinds or any(kind.startswith(p) for p in prefixes):
            continue
        findings.append(Finding(
            "KO-P013", rel, node.lineno,
            f"event kind {kind!r} does not resolve in the EventKind "
            f"vocabulary (observability/events.py) — a typo here "
            f"streams events no filter or story reducer ever selects; "
            f"add the kind to EventKind (or use an existing member)",
        ))
    return findings


# ---------------------------------------------------------------- KO-P014 ---
_P014_WAIVER = "KO-P014: waived"


def check_thread_discipline(root: str, tree: ast.AST, path: str) -> list:
    """Service-layer code (any file under `service/`) may not construct
    raw threads: concurrency there rides the shared `adm/pool.py
    BoundedPool` (deterministic launch order, fatal-BaseException crash
    semantics, settle-in-arrival-order), and the few legitimate non-pool
    threads — engine hosts, the cron loop, fire-and-forget dispatches —
    funnel through `utils/threads.spawn` so every one is named and
    daemonized. A bare `threading.Thread(...)` bypasses both: an
    anonymous undaemonized thread that outlives close() and swallows
    BaseExceptions the pool would surface. Genuinely special cases carry
    a `# KO-P014: waived — <reason>` comment."""
    relpath = os.path.relpath(path, root)
    if not relpath.startswith("service" + os.sep):
        return []
    findings: list = []
    candidates: list = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        bare = (
            (isinstance(func, ast.Attribute) and func.attr == "Thread"
             and isinstance(func.value, ast.Name)
             and func.value.id == "threading")
            or (isinstance(func, ast.Name) and func.id == "Thread")
        )
        if bare:
            candidates.append(node.lineno)
    if not candidates:
        return []
    with open(path, encoding="utf-8") as f:
        source_lines = f.read().splitlines()

    def waived(lineno: int) -> bool:
        lo = max(lineno - 4, 0)
        return any(_P014_WAIVER in line
                   for line in source_lines[lo:lineno])

    rel = _rel(root, path)
    for lineno in candidates:
        if waived(lineno):
            continue
        findings.append(Finding(
            "KO-P014", rel, lineno,
            "bare threading.Thread in the service layer — run the work "
            "on the shared adm/pool.py BoundedPool, or spawn the thread "
            "through utils/threads.spawn (named + daemonized), or waive "
            f"with `# {_P014_WAIVER} — <reason>`",
        ))
    return findings


# ---------------------------------------------------------------- KO-P015 ---
# vocabulary cache: root -> frozenset of declared metric family names;
# parsing api/metrics.py once per analyzed tree, not once per file
_P015_VOCAB: dict = {}

# the classic-format series suffixes a family name may legitimately grow
# when a row is rendered by hand (histogram series, counter series)
_P015_SUFFIXES = ("_bucket", "_sum", "_count", "_total")


def _metric_family_vocabulary(root: str) -> frozenset:
    """The METRIC_FAMILIES tuple parsed out of the ANALYZED tree's
    api/metrics.py — the registry's one declared alphabet of exposition
    family names. A tree that ships no metrics.py (fixture trees) falls
    back to the installed package's vocabulary, mirroring KO-P013."""
    if root in _P015_VOCAB:
        return _P015_VOCAB[root]
    names: set = set()
    path = os.path.join(root, "api", "metrics.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        tree = None
    assign = None
    if tree is not None:
        for node in tree.body:
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name)
                            and t.id == "METRIC_FAMILIES"
                            for t in node.targets)):
                assign = node
    if assign is not None and isinstance(assign.value, (ast.Tuple, ast.List)):
        for elt in assign.value.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                names.add(elt.value)
    if not names:
        from kubeoperator_tpu.api.metrics import METRIC_FAMILIES

        names.update(METRIC_FAMILIES)
    vocab = frozenset(names)
    _P015_VOCAB[root] = vocab
    return vocab


def check_metric_name_discipline(root: str, tree: ast.AST,
                                 path: str) -> list:
    """Every LITERAL metric family name reaching the exposition registry
    — the first positional or `name=` argument of a `family(...)`,
    `histogram(...)` or `_fmt(...)` call — must resolve in the
    METRIC_FAMILIES vocabulary (api/metrics.py): exactly, or as a
    declared family plus a classic-format series suffix (_bucket, _sum,
    _count, _total). A typo'd family name renders series no recording
    rule, dashboard, or golden exposition test ever selects — silently
    lost telemetry, the metric twin of KO-P013's event-kind rule.
    Computed names (f-strings, variables, concatenation) pass — they
    resolve from a vocabulary member at runtime."""
    findings: list = []
    rel = _rel(root, path)
    vocab = _metric_family_vocabulary(root)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        fname = func.id if isinstance(func, ast.Name) else (
            func.attr if isinstance(func, ast.Attribute) else "")
        if fname not in ("family", "histogram", "_fmt"):
            continue
        name_arg = node.args[0] if node.args else None
        for kw in node.keywords:
            if kw.arg == "name":
                name_arg = kw.value
        if not (isinstance(name_arg, ast.Constant)
                and isinstance(name_arg.value, str)):
            continue
        name = name_arg.value
        if name in vocab or any(
                name == member + suffix
                for member in vocab for suffix in _P015_SUFFIXES):
            continue
        findings.append(Finding(
            "KO-P015", rel, node.lineno,
            f"metric family name {name!r} does not resolve in the "
            f"METRIC_FAMILIES vocabulary (api/metrics.py) — a typo here "
            f"renders series no dashboard or golden exposition test ever "
            f"selects; add the family to METRIC_FAMILIES (or use a "
            f"declared one)",
        ))
    return findings


AST_RULES = {
    "KO-P001": check_repo_layering,
    "KO-P002": check_blocking_handlers,
    "KO-P004": check_mutable_defaults,
    "KO-P005": check_bare_except,
    "KO-P006": check_subprocess_timeouts,
    "KO-P007": check_phase_write_discipline,
    "KO-P011": check_checkpoint_atomic_writes,
    "KO-P012": check_event_discipline,
    "KO-P013": check_event_kind_discipline,
    "KO-P014": check_thread_discipline,
    "KO-P015": check_metric_name_discipline,
}


def run_ast_rules(root: str, rule_ids=None) -> tuple:
    """Parse each package file once, apply the selected rules to the shared
    tree. Returns (findings, files_scanned). A syntactically broken file
    raises — the gate must hard-fail (exit 2), not report it as a lint
    finding that a --format json consumer might filter away."""
    selected = {
        rid: fn for rid, fn in AST_RULES.items()
        if rule_ids is None or rid in rule_ids
    }
    findings: list = []
    scanned = 0
    for path in iter_python_files(root):
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        scanned += 1
        for fn in selected.values():
            findings.extend(fn(root, tree, path))
    return findings, scanned
