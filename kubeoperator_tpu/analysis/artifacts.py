"""Cross-artifact linter: the declarative surface checked as a whole.

The platform's correctness lives mostly in artifacts no interpreter ever
parses until a deploy is already running — phase playbooks, 40+ content
roles, jinja manifest templates, the offline bundle contract, SQL
migrations, TPU plan topology. Each rule here resolves one cross-artifact
reference class statically so a broken reference dies in `koctl lint` / CI,
not at phase 7 of a real cluster create.

Every rule is a pure function (AnalysisContext) -> list[Finding] and takes
optional injection parameters so tests can aim it at fixture trees without
stubbing imports.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass

import yaml

from kubeoperator_tpu.analysis.report import Finding


@dataclass
class AnalysisContext:
    """Where the artifacts live. `root` is the package dir (the default) or
    a fixture tree shaped like one; reported paths are relative to its
    parent so they read `kubeoperator_tpu/content/...` in real runs.

    File text is cached per path: several rules walk the same content tree,
    and the cache keeps that one read per file — which also makes
    `files_scanned` count files, not reads."""

    root: str
    plan_files: tuple = ()
    files_scanned: int = 0

    def __post_init__(self) -> None:
        self._text_cache: dict = {}
        self._content_lines: list | None = None

    @property
    def content_dir(self) -> str:
        return os.path.join(self.root, "content")

    @property
    def roles_dir(self) -> str:
        return os.path.join(self.content_dir, "roles")

    @property
    def playbooks_dir(self) -> str:
        return os.path.join(self.content_dir, "playbooks")

    @property
    def migrations_dir(self) -> str:
        return os.path.join(self.root, "repository", "migrations")

    def rel(self, path: str) -> str:
        return os.path.relpath(path, os.path.dirname(self.root) or ".")

    def roles(self) -> list:
        if not os.path.isdir(self.roles_dir):
            return []
        return sorted(
            d for d in os.listdir(self.roles_dir)
            if os.path.isdir(os.path.join(self.roles_dir, d))
        )

    def playbooks(self) -> list:
        if not os.path.isdir(self.playbooks_dir):
            return []
        return sorted(
            f for f in os.listdir(self.playbooks_dir) if f.endswith(".yml")
        )

    def content_lines(self) -> list:
        """(path, lines) for every content text file — the tree is walked
        and each file split ONCE, shared by the line-scanning rules
        (KO-X005/X007/X008)."""
        if self._content_lines is None:
            self._content_lines = [
                (path, self.read(path).splitlines())
                for path in _iter_content_text_files(self)
            ]
        return self._content_lines

    def load_yaml(self, path: str):
        return yaml.safe_load(self.read(path))

    def read(self, path: str) -> str:
        if path not in self._text_cache:
            with open(path, encoding="utf-8") as f:
                self._text_cache[path] = f.read()
            self.files_scanned += 1
        return self._text_cache[path]


def _task_module_arg(task: dict, *modules: str):
    """Fetch a module's args from a task dict, tolerating both bare
    (`template:`) and FQCN (`ansible.builtin.template:`) spellings."""
    for mod in modules:
        for key in (mod, f"ansible.builtin.{mod}"):
            if key in task:
                return task[key]
    return None


def _iter_role_task_files(ctx: AnalysisContext):
    for role in ctx.roles():
        tasks_dir = os.path.join(ctx.roles_dir, role, "tasks")
        if not os.path.isdir(tasks_dir):
            continue
        for fn in sorted(os.listdir(tasks_dir)):
            if fn.endswith((".yml", ".yaml")):
                yield role, os.path.join(tasks_dir, fn)


# ---------------------------------------------------------------- KO-X001 ---
def check_role_resolution(ctx: AnalysisContext) -> list:
    """Playbook `roles:` entries resolve to real roles; every role has an
    entry point. Dangling roles are the classic drift: a role rename that
    missed one playbook fails at runtime with ansible's least helpful
    error."""
    findings: list = []
    known = set(ctx.roles())
    for role in sorted(known):
        main = os.path.join(ctx.roles_dir, role, "tasks", "main.yml")
        if not os.path.exists(main):
            findings.append(Finding(
                "KO-X001", ctx.rel(os.path.join(ctx.roles_dir, role)), 0,
                f"role {role!r} has no tasks/main.yml entry point",
            ))
    for pb in ctx.playbooks():
        path = os.path.join(ctx.playbooks_dir, pb)
        try:
            plays = ctx.load_yaml(path) or []
        except yaml.YAMLError as e:
            findings.append(Finding(
                "KO-X001", ctx.rel(path), 0, f"unparseable playbook: {e}"
            ))
            continue
        if not isinstance(plays, list):
            continue  # shape findings belong to KO-X003
        for play in plays:
            if not isinstance(play, dict):
                continue
            for entry in play.get("roles") or []:
                name = entry.get("role") if isinstance(entry, dict) else entry
                if not isinstance(name, str):
                    continue
                if name not in known or not os.path.exists(os.path.join(
                        ctx.roles_dir, name, "tasks", "main.yml")):
                    findings.append(Finding(
                        "KO-X001", ctx.rel(path), 0,
                        f"playbook references missing role {name!r}",
                    ))
    return findings


# ---------------------------------------------------------------- KO-X002 ---
# literal filenames worth resolving when they appear inside a jinja
# expression (the tpu-smoke-test conditional src pattern)
_LITERAL_CANDIDATE_RE = re.compile(
    r"'([\w.-]+\.(?:j2|yml|yaml|py|sh|conf|cfg|toml|repo))'"
)


def _src_candidates(src: str) -> tuple:
    """(candidates, computed): literal filenames to resolve, and whether the
    source is runtime-computed (jinja with no literal file candidates —
    exempt, the linter cannot know the rendered value)."""
    if "{{" not in src and "{%" not in src:
        return (src,), False
    candidates = tuple(_LITERAL_CANDIDATE_RE.findall(src))
    return candidates, not candidates


def check_file_resolution(ctx: AnalysisContext) -> list:
    """template/copy/script sources and include_tasks targets resolve on
    disk. Search path mirrors ansible's: templates/ for the template
    module, files/ then templates/ for copy/script, the including file's
    dir for include_tasks (which also covers the repo's cross-role
    `../../role/tasks/x.yml` composition idiom)."""
    findings: list = []
    for role, path in _iter_role_task_files(ctx):
        try:
            tasks = ctx.load_yaml(path) or []
        except yaml.YAMLError as e:
            findings.append(Finding(
                "KO-X002", ctx.rel(path), 0, f"unparseable task file: {e}"
            ))
            continue
        role_dir = os.path.join(ctx.roles_dir, role)
        for task in tasks if isinstance(tasks, list) else []:
            if not isinstance(task, dict):
                continue
            for modules, search in (
                (("template",), ("templates",)),
                (("copy", "script"), ("files", "templates")),
            ):
                args = _task_module_arg(task, *modules)
                src = args.get("src") if isinstance(args, dict) else None
                if not isinstance(src, str) or src.startswith("/"):
                    continue  # node-absolute paths live on the target host
                candidates, computed = _src_candidates(src)
                if computed:
                    continue
                for cand in candidates:
                    if cand.startswith("/"):
                        continue
                    if not any(
                        os.path.exists(os.path.join(role_dir, d, cand))
                        for d in search
                    ):
                        findings.append(Finding(
                            "KO-X002", ctx.rel(path), 0,
                            f"role {role!r}: src {cand!r} not found under "
                            f"{' or '.join(search)}/",
                        ))
            inc = _task_module_arg(task, "include_tasks", "import_tasks")
            target = inc.get("file") if isinstance(inc, dict) else inc
            if isinstance(target, str) and "{{" not in target:
                resolved = os.path.normpath(
                    os.path.join(os.path.dirname(path), target)
                )
                if not os.path.exists(resolved):
                    findings.append(Finding(
                        "KO-X002", ctx.rel(path), 0,
                        f"role {role!r}: include_tasks target {target!r} "
                        f"does not exist",
                    ))
    return findings


# ---------------------------------------------------------------- KO-X003 ---
def _default_referenced_playbooks() -> dict:
    """Playbooks the python layer launches, by referencing symbol — the adm
    phase lists plus the component catalog."""
    import kubeoperator_tpu.adm.phases as phases_mod
    from kubeoperator_tpu.models.component import COMPONENT_CATALOG

    refs: dict = {}
    for name in dir(phases_mod):
        if name.endswith("_phases") and not name.startswith("_"):
            for phase in getattr(phases_mod, name)():
                refs.setdefault(phase.playbook, set()).add(
                    f"adm/phases.py:{name}"
                )
    for comp, entry in COMPONENT_CATALOG.items():
        for key in ("playbook", "uninstall_playbook"):
            if entry.get(key):
                refs.setdefault(entry[key], set()).add(
                    f"models/component.py:{comp}"
                )
    refs.setdefault("component-uninstall.yml", {"models/component.py"})
    return refs


def check_phase_playbooks(ctx: AnalysisContext, referenced: dict | None = None
                          ) -> list:
    findings: list = []
    present = set(ctx.playbooks())
    referenced = (_default_referenced_playbooks()
                  if referenced is None else referenced)
    for playbook, sources in sorted(referenced.items()):
        if playbook not in present:
            findings.append(Finding(
                "KO-X003", ctx.rel(ctx.playbooks_dir), 0,
                f"playbook {playbook!r} (referenced by "
                f"{', '.join(sorted(sources))}) is missing",
            ))
    for pb in sorted(present):
        path = os.path.join(ctx.playbooks_dir, pb)
        try:
            plays = ctx.load_yaml(path)
        except yaml.YAMLError:
            continue  # reported by KO-X001
        if not isinstance(plays, list) or not plays:
            findings.append(Finding(
                "KO-X003", ctx.rel(path), 0,
                "playbook must be a non-empty list of plays",
            ))
            continue
        for play in plays:
            if not isinstance(play, dict) or "hosts" not in play:
                findings.append(Finding(
                    "KO-X003", ctx.rel(path), 0,
                    "play is missing its hosts: pattern",
                ))
    return findings


# ---------------------------------------------------------------- KO-X004 ---
def _catalog_sizes(gen) -> list:
    sizes = set(gen.single_host_chip_sizes) | {16, 32, 64, 128, 256}
    return sorted(
        s for s in sizes
        if s <= gen.max_chips
        and (s in gen.single_host_chip_sizes or s % gen.chips_per_host == 0)
    )


def check_plan_topology(ctx: AnalysisContext) -> list:
    """The topology math everything downstream treats as ground truth: every
    selectable catalog shape must resolve and self-validate (mesh product ==
    chips, host math), and any plan YAML passed with --plan must survive the
    full Plan.validate() (provider capability + derived host count)."""
    from kubeoperator_tpu.models.infra import PLAN_FIELDS, Plan
    from kubeoperator_tpu.parallel.topology import GENERATIONS, parse_accelerator_type
    from kubeoperator_tpu.utils.errors import KoError

    findings: list = []
    topo_file = "kubeoperator_tpu/parallel/topology.py"
    for gen in GENERATIONS.values():
        for chips in _catalog_sizes(gen):
            name = f"{gen.name}-{gen.suffix_from_chips(chips)}"
            try:
                topo = parse_accelerator_type(name)
            except KoError as e:
                findings.append(Finding(
                    "KO-X004", topo_file, 0,
                    f"catalog shape {name}: {e.message}",
                ))
                continue
            import math

            if math.prod(topo.ici_mesh) != topo.chips:
                findings.append(Finding(
                    "KO-X004", topo_file, 0,
                    f"{name}: derived mesh {topo.gcp_topology} has "
                    f"{math.prod(topo.ici_mesh)} chips, slice has "
                    f"{topo.chips}",
                ))
            if (topo.hosts_per_slice > 1
                    and topo.hosts_per_slice * gen.chips_per_host
                    != topo.chips):
                findings.append(Finding(
                    "KO-X004", topo_file, 0,
                    f"{name}: {topo.hosts_per_slice} hosts x "
                    f"{gen.chips_per_host} chips/host != {topo.chips}",
                ))
        if not gen.default_runtime_version:
            findings.append(Finding(
                "KO-X004", topo_file, 0,
                f"generation {gen.name} has no default runtime version",
            ))

    for plan_file in ctx.plan_files:
        try:
            doc = ctx.load_yaml(plan_file)
        except (OSError, yaml.YAMLError) as e:
            findings.append(Finding(
                "KO-X004", plan_file, 0, f"unreadable plan file: {e}"
            ))
            continue
        plans = doc.get("plans", [doc]) if isinstance(doc, dict) else []
        if not isinstance(plans, list) or not plans:
            findings.append(Finding(
                "KO-X004", plan_file, 0, "no plan mapping in file"
            ))
            continue
        for raw in plans:
            if not isinstance(raw, dict):
                continue
            name = str(raw.get("name", "") or "<unnamed>")
            # TypeError/ValueError too, not just KoError: a dirty plan file
            # (master_count: "three") is a FINDING (exit 1), never an
            # analyzer crash (exit 2 means the gate itself is broken)
            try:
                plan = Plan(**{k: raw[k] for k in PLAN_FIELDS if k in raw})
                plan.validate()
                if plan.has_tpu():
                    plan.topology().validate()
            except KoError as e:
                findings.append(Finding(
                    "KO-X004", plan_file, 0, f"plan {name}: {e.message}",
                ))
            except (TypeError, ValueError) as e:
                findings.append(Finding(
                    "KO-X004", plan_file, 0,
                    f"plan {name}: malformed plan mapping: {e}",
                ))
    return findings


# ---------------------------------------------------------------- KO-X005 ---
# `{{ registry_url ... }}/path/to/image:{{ tag_var ... }}` (or literal tag)
_IMAGE_REF_RE = re.compile(
    r"\{\{\s*registry_(?:url|host)[^}]*\}\}/"
    r"(?P<path>[A-Za-z0-9._/-]+):"
    r"(?P<tag>\{\{\s*(?P<tagvar>[A-Za-z_][A-Za-z0-9_]*)[^}]*\}\}|[\w.-]+)"
)


def _iter_content_text_files(ctx: AnalysisContext):
    for base, _dirs, files in os.walk(ctx.content_dir):
        for fn in sorted(files):
            if fn.endswith((".yml", ".yaml", ".j2", ".toml", ".repo")):
                yield os.path.join(base, fn)


def check_image_pins(ctx: AnalysisContext, contract: dict | None = None,
                     artifacts: list | None = None) -> list:
    """Every image reference a template renders must be declared in the
    offline bundle's image contract with the tag var the contract pins, and
    the contract's tarball must be in the bundle manifest — so an air-gapped
    cluster can never be told to pull an image the bundle doesn't carry."""
    if contract is None:
        from kubeoperator_tpu.registry.manifest import TEMPLATED_IMAGES

        contract = TEMPLATED_IMAGES
    if artifacts is None:
        from kubeoperator_tpu.registry.manifest import bundle_manifest

        artifacts = bundle_manifest()["artifacts"]
    findings: list = []
    for path, lines in ctx.content_lines():
        for lineno, line in enumerate(lines, 1):
            for m in _IMAGE_REF_RE.finditer(line):
                image = m.group("path")
                entry = contract.get(image)
                if entry is None:
                    findings.append(Finding(
                        "KO-X005", ctx.rel(path), lineno,
                        f"image {image!r} is not in the offline bundle "
                        f"image contract (registry/manifest.py "
                        f"TEMPLATED_IMAGES)",
                    ))
                    continue
                tag_var, tarball = entry
                rendered_var = m.group("tagvar")
                if rendered_var != tag_var:
                    got = rendered_var or f"literal {m.group('tag')!r}"
                    findings.append(Finding(
                        "KO-X005", ctx.rel(path), lineno,
                        f"image {image!r} tag renders from {got}; the "
                        f"bundle contract pins it via {tag_var!r}",
                    ))
                if tarball not in artifacts:
                    findings.append(Finding(
                        "KO-X005", ctx.rel(path), lineno,
                        f"image {image!r}: contract tarball {tarball!r} is "
                        f"missing from the bundle manifest",
                    ))
    return findings


# ---------------------------------------------------------------- KO-X006 ---
def check_migrations(ctx: AnalysisContext) -> list:
    """Migration files must form an unbroken, unambiguous 001..N sequence of
    complete SQL: a gap or duplicate number silently skips (or re-skips)
    DDL at boot, and an incomplete trailing statement would die mid-
    transaction on the next fresh install."""
    findings: list = []
    mig_dir = ctx.migrations_dir
    if not os.path.isdir(mig_dir):
        return findings
    # the boot runner's OWN naming/splitting rules — importing them (not
    # copying) is the point: the linter validates exactly the contract
    # Database.migrate() executes
    from kubeoperator_tpu.repository.db import (
        _MIGRATION_RE,
        _split_statements,
        statement_is_complete,
    )

    seen: dict = {}
    numbers: list = []
    for fname in sorted(os.listdir(mig_dir)):
        path = os.path.join(mig_dir, fname)
        if not os.path.isfile(path):
            continue
        m = _MIGRATION_RE.match(fname)
        if not m:
            findings.append(Finding(
                "KO-X006", ctx.rel(path), 0,
                "migration name must match NNN_slug.sql (the boot runner "
                "ignores anything else, so this file would never apply)",
            ))
            continue
        version = m.group(1)
        if version in seen:
            findings.append(Finding(
                "KO-X006", ctx.rel(path), 0,
                f"duplicate migration number {version} (also {seen[version]}); "
                f"only one of them will ever be recorded as applied",
            ))
        else:
            seen[version] = fname
            numbers.append(int(version))
        statements = _split_statements(ctx.read(path))
        if not statements:
            findings.append(Finding(
                "KO-X006", ctx.rel(path), 0, "migration contains no SQL"
            ))
        for stmt in statements:
            if not statement_is_complete(stmt):
                findings.append(Finding(
                    "KO-X006", ctx.rel(path), 0,
                    f"incomplete SQL statement (missing ';'?): "
                    f"{stmt.splitlines()[0][:60]!r}",
                ))
    expected = list(range(1, len(numbers) + 1))
    if numbers and sorted(numbers) != expected:
        missing = sorted(set(expected) - set(numbers))
        findings.append(Finding(
            "KO-X006", ctx.rel(mig_dir), 0,
            f"migration numbering has gaps: missing "
            f"{', '.join(f'{n:03d}' for n in missing)}",
        ))
    return findings


# ---------------------------------------------------------------- KO-X007 ---
_MANIFEST_REF_RE = re.compile(r"/opt/ko-manifests/([\w.-]+)")


def check_manifest_refs(ctx: AnalysisContext, bundled: tuple | None = None,
                        generated: tuple | None = None) -> list:
    """Files roles apply from /opt/ko-manifests/ must be bundle-shipped, and
    every generated manifest must be listed as bundled — drift in either
    direction strands a role (apply of a file the installer never wrote) or
    the bundle (a generator whose output nothing ships)."""
    if bundled is None:
        from kubeoperator_tpu.registry.k8s_manifests import BUNDLED_MANIFESTS

        bundled = BUNDLED_MANIFESTS
    if generated is None:
        from kubeoperator_tpu.registry.k8s_manifests import GENERATED

        generated = tuple(GENERATED)
    findings: list = []
    for path, lines in ctx.content_lines():
        for lineno, line in enumerate(lines, 1):
            for name in _MANIFEST_REF_RE.findall(line):
                if name not in bundled:
                    findings.append(Finding(
                        "KO-X007", ctx.rel(path), lineno,
                        f"/opt/ko-manifests/{name} is not in "
                        f"BUNDLED_MANIFESTS — the installer never ships it",
                    ))
    for name in generated:
        if name not in bundled:
            findings.append(Finding(
                "KO-X007", "kubeoperator_tpu/registry/k8s_manifests.py", 0,
                f"generated manifest {name!r} is not listed in "
                f"BUNDLED_MANIFESTS",
            ))
    return findings


# ---------------------------------------------------------------- KO-X008 ---
_VERSION_VAR_RE = re.compile(
    r"\{\{\s*([A-Za-z_][A-Za-z0-9_]*_version)(?![A-Za-z0-9_])([^}]*)\}\}"
)


def _default_supplied_version_vars() -> frozenset:
    from kubeoperator_tpu.registry.manifest import COMPONENT_VERSIONS

    return frozenset(
        {f"{k}_version" for k in COMPONENT_VERSIONS}
        | {"tpu_runtime_version", "k8s_version"}
    )


def check_version_vars(ctx: AnalysisContext, supplied: frozenset | None = None
                       ) -> list:
    """Every `*_version` var content consumes must be supplied by the
    engine's extra-vars contract or carry an inline default — otherwise the
    template renders an AnsibleUndefined into a manifest on a real node."""
    if supplied is None:
        supplied = _default_supplied_version_vars()
    findings: list = []
    for path, lines in ctx.content_lines():
        for lineno, line in enumerate(lines, 1):
            for var, rest in _VERSION_VAR_RE.findall(line):
                if var in supplied or "default(" in rest:
                    continue
                findings.append(Finding(
                    "KO-X008", ctx.rel(path), lineno,
                    f"version var {var!r} is not supplied by the extra-vars "
                    f"contract and has no inline default",
                ))
    return findings


# ---------------------------------------------------------------- KO-X011 ---
def _default_phase_families() -> dict:
    """{family name: [Phase, ...]} for every adm phase family — the same
    `*_phases` enumeration KO-X003 uses for playbook references."""
    import kubeoperator_tpu.adm.phases as phases_mod

    return {
        name: getattr(phases_mod, name)()
        for name in dir(phases_mod)
        if name.endswith("_phases") and not name.startswith("_")
    }


def check_phase_dags(ctx: AnalysisContext, families: dict | None = None
                     ) -> list:
    """KO-X011 — the DAG contract the scheduler (adm/dag.py) relies on,
    enforced before a bad edge can deadlock or misorder a live create:
    every `Phase.after` edge resolves to an EARLIER-declared phase in the
    same family (backward edges ⇒ acyclic ⇒ declaration order stays a
    valid serial schedule ⇒ ready-order is a deterministic function of
    declaration order), and names are unique. `families` is injectable so
    tests can aim the rule at fixture families."""
    from kubeoperator_tpu.adm.dag import validate_family

    findings: list = []
    families = (_default_phase_families() if families is None else families)
    for fam_name in sorted(families):
        for problem in validate_family(families[fam_name]):
            findings.append(Finding(
                "KO-X011", "kubeoperator_tpu/adm/phases.py", 0,
                f"phase family {fam_name}: {problem}",
            ))
    return findings


# ---------------------------------------------------------------- KO-X012 ---
_MEGASCALE_VAR = "MEGASCALE_COORDINATOR_ADDRESS"
_JOBSET_KIND_RE = re.compile(r"^\s*kind:\s*JobSet\s*$", re.MULTILINE)


def _multislice_plan_decls(ctx: AnalysisContext) -> list:
    """(plan file, plan name, num_slices) for every --plan plan declaring
    num_slices > 1 (malformed files are KO-X004's problem, not ours)."""
    out = []
    for plan_file in ctx.plan_files:
        try:
            doc = ctx.load_yaml(plan_file)
        except (OSError, yaml.YAMLError):
            continue
        plans = doc.get("plans", [doc]) if isinstance(doc, dict) else []
        if not isinstance(plans, list):
            continue
        for raw in plans:
            if not isinstance(raw, dict):
                continue
            try:
                n = int(raw.get("num_slices", 1))
            except (TypeError, ValueError):
                continue
            if n > 1:
                out.append((plan_file,
                            str(raw.get("name") or "<unnamed>"), n))
    return out


def check_multislice_launch(ctx: AnalysisContext, plans: list | None = None
                            ) -> list:
    """KO-X012 — the multislice launch contract: a plan declaring
    ``num_slices > 1`` is a promise that the content layer can LAUNCH
    DCN-connected slices, which means (a) a JobSet manifest template
    exists (``kind: JobSet``), (b) some role task actually references it
    as a launch path, and (c) the template wires the megascale
    coordinator var — without `MEGASCALE_COORDINATOR_ADDRESS` the slices
    boot as N independent single-slice runtimes and every cross-slice
    collective hangs, a failure mode only visible minutes into a real
    workload. Every existing JobSet template is held to (c) regardless of
    plans, so stripping the megascale block from the smoke JobSet fires
    even with no --plan file in hand."""
    findings: list = []
    jobset_templates: list = []     # (role, filename, path, text)
    for role in ctx.roles():
        tdir = os.path.join(ctx.roles_dir, role, "templates")
        if not os.path.isdir(tdir):
            continue
        for fn in sorted(os.listdir(tdir)):
            if not fn.endswith((".j2", ".yml", ".yaml")):
                continue
            path = os.path.join(tdir, fn)
            text = ctx.read(path)
            if _JOBSET_KIND_RE.search(text):
                jobset_templates.append((role, fn, path, text))

    megascale_ok = False
    for _role, _fn, path, text in jobset_templates:
        if _MEGASCALE_VAR in text:
            megascale_ok = True
        else:
            findings.append(Finding(
                "KO-X012", ctx.rel(path), 0,
                f"JobSet launch template renders no {_MEGASCALE_VAR} — a "
                f"num_slices > 1 plan would boot its slices as "
                f"disconnected single-slice runtimes",
            ))

    referenced = False
    if jobset_templates:
        names = {fn for _role, fn, _path, _text in jobset_templates}
        for _role, task_file in _iter_role_task_files(ctx):
            text = ctx.read(task_file)
            if any(name in text for name in names):
                referenced = True
                break
        if not referenced:
            findings.append(Finding(
                "KO-X012", ctx.rel(ctx.roles_dir), 0,
                "a JobSet launch template exists but no role task "
                "references it — the multislice launch path is dead code",
            ))

    plans = _multislice_plan_decls(ctx) if plans is None else plans
    for plan_file, plan_name, n in plans:
        if not jobset_templates:
            findings.append(Finding(
                "KO-X012", plan_file, 0,
                f"plan {plan_name} declares num_slices={n} but the "
                f"content tree has no JobSet launch template "
                f"(kind: JobSet) to schedule its slices with",
            ))
        elif not (megascale_ok and referenced):
            findings.append(Finding(
                "KO-X012", plan_file, 0,
                f"plan {plan_name} declares num_slices={n} but the "
                f"JobSet launch path is not fully wired (megascale "
                f"coordinator var or role-task reference missing)",
            ))
    return findings


ARTIFACT_RULES = {
    "KO-X001": check_role_resolution,
    "KO-X002": check_file_resolution,
    "KO-X003": check_phase_playbooks,
    "KO-X004": check_plan_topology,
    "KO-X005": check_image_pins,
    "KO-X006": check_migrations,
    "KO-X007": check_manifest_refs,
    "KO-X008": check_version_vars,
    "KO-X011": check_phase_dags,
    "KO-X012": check_multislice_launch,
}
