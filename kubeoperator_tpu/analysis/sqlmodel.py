"""SQL substrate for the KO-S rule family: the migration-derived schema
model + the python-side SQL statement extractor.

Two halves, consumed by sqlrules.py:

* `build_schema_model()` folds repository/migrations/NNN_*.sql in order —
  CREATE TABLE / CREATE INDEX / ALTER TABLE ADD COLUMN — into ONE schema
  model (tables with ordered columns, every index including the implicit
  UNIQUE/PRIMARY KEY ones), recording which migration introduced each
  piece. The fold itself validates migration discipline (KO-S004's raw
  material): only additive statement forms are allowed, and nothing may
  reference a table/column before the migration that creates it. A golden
  test pins this model against live PRAGMA introspection of a freshly
  migrated database, so model and reality can never drift.

* `extract_sql_facts()` reduces one parsed python file to every SQL
  string that reaches a Database execute/query call site, resolved
  through class attributes (`self.table`, including the
  `table, entity, columns = ...` tuple-unpack idiom), module constants,
  the sanctioned dialect seams (db.py DB_NOW_SQL / ROWID_SQL — recorded
  per statement, excluded from the dialect scan), `'sep'.join(...)` over
  literal-element clause lists (superset semantics: every conditional
  append lands in the resolved text), and placeholder-generator joins.
  Statements with an unresolvable fragment are marked `dynamic`: the
  conformance/coverage rules skip them, the dialect rule still scans
  their literal fragments. Rides the PR-4 per-file fact index, so a warm
  run re-extracts nothing.

Layering: like the KO-X006 migration rule, SQL splitting goes through
repository/db.py's exported helpers — this module never imports sqlite3
itself (its own repo-layering rule, KO-P001).
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from kubeoperator_tpu.repository.db import (
    DB_NOW_SQL,
    ROWID_SQL,
    _MIGRATION_RE,
    statement_is_complete,
)

# the sanctioned dialect seams: interpolating one of these names into a
# SQL f-string is the contract (docs/resilience.md "SQL contract"); the
# constant's VALUE lands in the resolved text for schema checking but is
# excluded from the dialect scan
SEAM_VALUES = {"DB_NOW_SQL": DB_NOW_SQL, "ROWID_SQL": ROWID_SQL}

# marker substituted for an unresolvable f-string fragment — never valid
# SQL, so a dynamic statement can't accidentally parse as clean
DYNAMIC_MARK = "\x00?\x00"

_SQL_KEYWORDS = frozenset("""
    select from where and or not in is null order by group having limit
    offset desc asc as on join left right inner outer cross delete insert
    into values update set conflict do nothing union all case when then
    else end like escape between exists distinct pragma create table if
    index primary key unique references default begin immediate exclusive
    deferred commit rollback alter add column drop rename to text integer
    real blob collate
""".split())

_STRING_RE = re.compile(r"'(?:[^']|'')*'")
_IDENT_RE = re.compile(r"[A-Za-z_]\w*")
_TABLE_REF_RE = re.compile(r"\b(?:FROM|INTO|JOIN|UPDATE)\s+([A-Za-z_]\w*)",
                           re.IGNORECASE)
_AS_RE = re.compile(r"\bAS\s+([A-Za-z_]\w*)", re.IGNORECASE)
_QUALIFIED_RE = re.compile(r"\b([A-Za-z_]\w*)\.([A-Za-z_]\w*)")


def mask_strings(sql: str) -> str:
    """Replace SQL string-literal contents with '' so literal text can't
    masquerade as identifiers."""
    return _STRING_RE.sub("''", sql)


def strip_sql_comments(sql: str) -> str:
    """Drop `-- ...` comments (line-wise; a `--` after an odd number of
    quotes is inside a string literal and survives)."""
    out = []
    for line in sql.splitlines():
        pos = line.find("--")
        while pos != -1:
            if line[:pos].count("'") % 2 == 0:
                line = line[:pos]
                break
            pos = line.find("--", pos + 1)
        out.append(line)
    return "\n".join(out)


# ------------------------------------------------------------ schema model --
@dataclass
class TableModel:
    name: str
    columns: list = field(default_factory=list)      # ordered column names
    created_in: str = ""                             # migration version
    column_origin: dict = field(default_factory=dict)  # col -> version


@dataclass
class IndexModel:
    name: str
    table: str
    columns: list
    unique: bool
    origin: str        # "c" CREATE INDEX | "u" UNIQUE constraint | "pk"
    created_in: str


@dataclass
class SchemaModel:
    """The folded migration state: what exists after NNN migrations."""

    tables: dict = field(default_factory=dict)    # name -> TableModel
    indexes: dict = field(default_factory=dict)   # name -> IndexModel

    def table_indexes(self, table: str) -> list:
        return [i for i in self.indexes.values() if i.table == table]

    def has_column(self, table: str, column: str) -> bool:
        t = self.tables.get(table)
        return t is not None and column in t.columns


_CREATE_TABLE_RE = re.compile(
    r"^CREATE\s+TABLE\s+(?:IF\s+NOT\s+EXISTS\s+)?([A-Za-z_]\w*)\s*\((.*)\)"
    r"\s*;?\s*$", re.IGNORECASE | re.DOTALL)
_CREATE_INDEX_RE = re.compile(
    r"^CREATE\s+(UNIQUE\s+)?INDEX\s+(?:IF\s+NOT\s+EXISTS\s+)?"
    r"([A-Za-z_]\w*)\s+ON\s+([A-Za-z_]\w*)\s*\(([^)]*)\)\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)
_ALTER_ADD_RE = re.compile(
    r"^ALTER\s+TABLE\s+([A-Za-z_]\w*)\s+ADD\s+COLUMN\s+([A-Za-z_]\w*)",
    re.IGNORECASE)
_REFERENCES_RE = re.compile(
    r"\bREFERENCES\s+([A-Za-z_]\w*)\s*\(\s*([A-Za-z_]\w*)\s*\)",
    re.IGNORECASE)
_TABLE_CONSTRAINT_HEADS = frozenset(
    {"unique", "primary", "foreign", "check", "constraint"})


def _split_top_level_commas(body: str) -> list:
    parts, depth, buf = [], 0, ""
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append(buf.strip())
            buf = ""
        else:
            buf += ch
    if buf.strip():
        parts.append(buf.strip())
    return parts


def iter_migration_statements(migrations_dir: str):
    """Yield (version, fname, statement_text, start_line) across every
    NNN_slug.sql in lexical order — the same split the boot runner applies
    (line-tracked so findings can point at the statement)."""
    for fname in sorted(os.listdir(migrations_dir)):
        if not _MIGRATION_RE.match(fname):
            continue
        version = fname[:3]
        with open(os.path.join(migrations_dir, fname),
                  encoding="utf-8") as f:
            lines = f.read().splitlines()
        buf, start = "", 0
        for n, line in enumerate(lines, 1):
            stripped = line.strip()
            if not buf and (not stripped or stripped.startswith("--")):
                continue
            if not buf:
                start = n
            buf += line + "\n"
            if statement_is_complete(buf):
                yield version, fname, buf.strip(), start
                buf = ""
        if buf.strip():
            yield version, fname, buf.strip(), start


def _fold_create_table(model: SchemaModel, name: str, body: str,
                       version: str, problems: list, where: tuple) -> None:
    table = TableModel(name=name, created_in=version)
    for item in _split_top_level_commas(body):
        head_m = re.match(r"[A-Za-z_]\w*", item)
        head = head_m.group(0).lower() if head_m else ""
        if head in _TABLE_CONSTRAINT_HEADS:
            m = re.match(r"^(UNIQUE|PRIMARY\s+KEY)\s*\(([^)]*)\)",
                         item, re.IGNORECASE)
            if m:
                cols = [c.split()[0] for c in m.group(2).split(",") if c.split()]
                origin = "pk" if m.group(1).upper().startswith("P") else "u"
                iname = f"{name}.{origin}.{'+'.join(cols)}"
                model.indexes[iname] = IndexModel(
                    iname, name, cols, True, origin, version)
            continue
        col = item.split()[0]
        table.columns.append(col)
        table.column_origin[col] = version
        rest = item[len(col):]
        if re.search(r"\bPRIMARY\s+KEY\b", rest, re.IGNORECASE):
            model.indexes[f"{name}.pk.{col}"] = IndexModel(
                f"{name}.pk.{col}", name, [col], True, "pk", version)
        elif re.search(r"\bUNIQUE\b", rest, re.IGNORECASE):
            model.indexes[f"{name}.u.{col}"] = IndexModel(
                f"{name}.u.{col}", name, [col], True, "u", version)
        for rm in _REFERENCES_RE.finditer(rest):
            rt, rc = rm.group(1), rm.group(2)
            if rt != name and not model.has_column(rt, rc):
                problems.append((*where,
                                 f"column {name}.{col} REFERENCES {rt}({rc}) "
                                 f"before any migration creates it"))
    model.tables[name] = table


def build_schema_model(migrations_dir: str) -> tuple:
    """Fold every migration into (SchemaModel, discipline_problems).

    Problems are (fname, line, message) rows — KO-S004's findings:
    non-additive statement forms (DROP / RENAME / other ALTERs / DML),
    and any statement referencing a table or column before the migration
    that creates it.
    """
    model = SchemaModel()
    # migration-000 bootstrap: db.py creates the version ledger itself,
    # before any migration runs — it is part of the schema contract
    model.tables["schema_migrations"] = TableModel(
        name="schema_migrations", columns=["version", "applied_at"],
        created_in="000",
        column_origin={"version": "000", "applied_at": "000"})
    model.indexes["schema_migrations.pk.version"] = IndexModel(
        "schema_migrations.pk.version", "schema_migrations",
        ["version"], True, "pk", "000")
    problems: list = []
    if not os.path.isdir(migrations_dir):
        return model, problems
    for version, fname, raw, line in iter_migration_statements(migrations_dir):
        stmt = strip_sql_comments(raw).strip()
        where = (fname, line)
        m = _CREATE_TABLE_RE.match(stmt)
        if m:
            name = m.group(1)
            if name in model.tables and \
                    not re.search(r"IF\s+NOT\s+EXISTS", stmt, re.IGNORECASE):
                problems.append((*where,
                                 f"CREATE TABLE {name} duplicates a table "
                                 f"created in migration "
                                 f"{model.tables[name].created_in}"))
            _fold_create_table(model, name, m.group(2), version, problems,
                               where)
            continue
        m = _CREATE_INDEX_RE.match(stmt)
        if m:
            unique, iname, table = bool(m.group(1)), m.group(2), m.group(3)
            cols = [c.split()[0] for c in m.group(4).split(",") if c.split()]
            if table not in model.tables:
                problems.append((*where,
                                 f"CREATE INDEX {iname} references table "
                                 f"{table} before any migration creates it"))
            else:
                missing = [c for c in cols
                           if not model.has_column(table, c)]
                if missing:
                    problems.append(
                        (*where,
                         f"CREATE INDEX {iname} references column(s) "
                         f"{', '.join(missing)} of {table} before the "
                         f"migration that creates them"))
            model.indexes[iname] = IndexModel(iname, table, cols, unique,
                                              "c", version)
            continue
        m = _ALTER_ADD_RE.match(stmt)
        if m:
            table, col = m.group(1), m.group(2)
            if table not in model.tables:
                problems.append((*where,
                                 f"ALTER TABLE {table} before any migration "
                                 f"creates it"))
            else:
                model.tables[table].columns.append(col)
                model.tables[table].column_origin[col] = version
            continue
        head = " ".join(stmt.split()[:3]).upper()
        problems.append((*where,
                         f"statement form not allowed in migrations "
                         f"(additive DDL only — CREATE TABLE, CREATE INDEX, "
                         f"ALTER TABLE ADD COLUMN): {head} ..."))
    return model, problems


# ----------------------------------------------------- statement tokenizing --
def parse_statement(text: str) -> dict:
    """Light lexical reduction of one resolved SQL statement: head verb,
    referenced tables (+ alias map), AS-defined aliases, qualified and
    bare identifier references — the raw material for KO-S001/KO-S003."""
    masked = mask_strings(text)
    words = masked.split()
    head = words[0].upper() if words else ""
    tables, alias_map = [], {}
    for m in _TABLE_REF_RE.finditer(masked):
        name = m.group(1)
        if name.lower() in _SQL_KEYWORDS:      # "DO UPDATE SET ..."
            continue
        if name not in tables:
            tables.append(name)
        after = masked[m.end():].lstrip()
        am = _IDENT_RE.match(after)
        if am and am.group(0).lower() not in _SQL_KEYWORDS:
            alias_map[am.group(0)] = name
    as_aliases = {m.group(1) for m in _AS_RE.finditer(masked)}
    qualified = [(q, c) for q, c in
                 ((m.group(1), m.group(2))
                  for m in _QUALIFIED_RE.finditer(masked))]
    qualified_spans = {m.start(2) for m in _QUALIFIED_RE.finditer(masked)}
    columns = []
    for m in _IDENT_RE.finditer(masked):
        word = m.group(0)
        low = word.lower()
        rest = masked[m.end():].lstrip()
        before = masked[:m.start()].rstrip()
        if low in _SQL_KEYWORDS or rest.startswith("("):
            continue                            # keyword or function call
        if before.endswith(".") or m.start() in qualified_spans \
                or rest.startswith("."):
            continue                            # part of a qualified ref
        if word in tables or word in alias_map or word in as_aliases:
            continue
        if DYNAMIC_MARK in text:
            continue
        columns.append((word, m.start()))
    return {"head": head, "tables": tables, "alias_map": alias_map,
            "as_aliases": as_aliases, "qualified": qualified,
            "columns": columns, "masked": masked}


# -------------------------------------------------- python fact extraction --
_DB_RECEIVERS = frozenset({"db", "conn", "_conn", "cur"})
_EXEC_METHODS = frozenset({"query", "execute", "executemany"})
_MAX_DEPTH = 12


def _receiver_tail(node) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _seam_name(node) -> str:
    """The seam constant an expression names, or ''. Accepts the bare
    imported Name and any dotted spelling ending in the seam name."""
    tail = node.attr if isinstance(node, ast.Attribute) else \
        (node.id if isinstance(node, ast.Name) else "")
    return tail if tail in SEAM_VALUES else ""


def _class_str_attrs(cls: ast.ClassDef) -> dict:
    """Class-level string/str-tuple attributes, covering both plain
    assignment and the `table, entity, columns = ...` unpack idiom."""
    attrs: dict = {}

    def record(name: str, value) -> None:
        if isinstance(value, ast.Constant) and isinstance(value.value, str):
            attrs[name] = value.value
        elif isinstance(value, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in value.elts):
            attrs[name] = [e.value for e in value.elts]

    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and stmt.value is not None and \
                isinstance(stmt.target, ast.Name):
            record(stmt.target.id, stmt.value)
        elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            target = stmt.targets[0]
            if isinstance(target, ast.Name):
                record(target.id, stmt.value)
            elif isinstance(target, ast.Tuple) and \
                    isinstance(stmt.value, ast.Tuple) and \
                    len(target.elts) == len(stmt.value.elts):
                for t, v in zip(target.elts, stmt.value.elts):
                    if isinstance(t, ast.Name):
                        record(t.id, v)
    return attrs


class _Resolved:
    """Accumulator for one resolved SQL expression: the full text (seams
    substituted by their SQL), the literal-only text (seam spans blanked
    — KO-S002's scan surface), seams used, and whether any fragment was
    unresolvable (dynamic)."""

    def __init__(self) -> None:
        self.text = ""
        self.literal = ""
        self.seams: list = []
        self.dynamic = False

    def add_literal(self, s: str) -> None:
        self.text += s
        self.literal += s

    def add_seam(self, name: str) -> None:
        self.text += SEAM_VALUES[name]
        self.literal += " "
        if name not in self.seams:
            self.seams.append(name)

    def add_dynamic(self) -> None:
        self.text += DYNAMIC_MARK
        self.literal += " "
        self.dynamic = True

    def merge(self, other: "_Resolved") -> None:
        self.text += other.text
        self.literal += other.literal
        for s in other.seams:
            if s not in self.seams:
                self.seams.append(s)
        self.dynamic = self.dynamic or other.dynamic


class _FunctionEnv:
    """Single-assignment local bindings + clause-list appends for one
    function body (superset semantics: conditional appends all count)."""

    def __init__(self, func) -> None:
        self.bindings: dict = {}
        self.appends: dict = {}
        for node in ast.walk(func):
            if isinstance(node, ast.Assign):
                pairs = []
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Tuple) and \
                        isinstance(node.value, ast.Tuple) and \
                        len(node.targets[0].elts) == len(node.value.elts):
                    pairs = list(zip(node.targets[0].elts, node.value.elts))
                else:
                    for target in node.targets:
                        pairs.append((target, node.value))
                for t, v in pairs:
                    if isinstance(t, ast.Name):
                        self.bindings[t.id] = (
                            "POISON" if t.id in self.bindings else v)
            elif isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Name):
                self.bindings[node.target.id] = "POISON"
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "append" and \
                    isinstance(node.func.value, ast.Name) and node.args:
                self.appends.setdefault(node.func.value.id,
                                        []).append(node.args[0])


class _SqlExtractor:
    def __init__(self, tree: ast.AST, rel: str) -> None:
        self.rel = rel
        self.module_consts: dict = {}
        self.statements: list = []
        self.classes: list = []
        if isinstance(tree, ast.Module):
            for node in tree.body:
                if isinstance(node, ast.Assign) and \
                        len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name) and \
                        isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, str):
                    self.module_consts[node.targets[0].id] = node.value.value
        self.tree = tree

    # ---- expression resolution ----
    def _resolve(self, node, cls_attrs: dict, env: _FunctionEnv,
                 depth: int = 0) -> _Resolved:
        out = _Resolved()
        if depth > _MAX_DEPTH:
            out.add_dynamic()
            return out
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            out.add_literal(node.value)
            return out
        if isinstance(node, ast.JoinedStr):
            for value in node.values:
                if isinstance(value, ast.Constant):
                    out.add_literal(str(value.value))
                elif isinstance(value, ast.FormattedValue):
                    out.merge(self._resolve_fragment(
                        value.value, cls_attrs, env, depth + 1))
            return out
        out.add_dynamic()
        return out

    def _resolve_fragment(self, expr, cls_attrs: dict, env: _FunctionEnv,
                          depth: int) -> _Resolved:
        out = _Resolved()
        if depth > _MAX_DEPTH:
            out.add_dynamic()
            return out
        seam = _seam_name(expr)
        if seam:
            out.add_seam(seam)
            return out
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            out.add_literal(expr.value)
            return out
        if isinstance(expr, ast.Name):
            bound = env.bindings.get(expr.id)
            if bound is not None and bound != "POISON":
                return self._resolve_fragment(bound, cls_attrs, env,
                                              depth + 1)
            if bound is None and expr.id in self.module_consts:
                out.add_literal(self.module_consts[expr.id])
                return out
            out.add_dynamic()
            return out
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            value = cls_attrs.get(expr.attr)
            if isinstance(value, str) and value:
                out.add_literal(value)
                return out
            out.add_dynamic()     # absent, empty ('' table) or non-str
            return out
        if isinstance(expr, ast.JoinedStr):
            return self._resolve(expr, cls_attrs, env, depth + 1)
        if isinstance(expr, ast.Call) and \
                isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "join" and len(expr.args) == 1 and \
                isinstance(expr.func.value, ast.Constant) and \
                isinstance(expr.func.value.value, str):
            return self._resolve_join(expr.func.value.value, expr.args[0],
                                      cls_attrs, env, depth + 1)
        out.add_dynamic()
        return out

    def _resolve_join(self, sep: str, arg, cls_attrs: dict,
                      env: _FunctionEnv, depth: int) -> _Resolved:
        out = _Resolved()
        # `','.join('?' for _ in xs)` — a placeholder list: one marker
        # stands in for N (schema/coverage-neutral either way)
        if isinstance(arg, ast.GeneratorExp) and \
                isinstance(arg.elt, ast.Constant) and \
                isinstance(arg.elt.value, str):
            out.add_literal(arg.elt.value)
            return out
        elements = None
        if isinstance(arg, (ast.List, ast.Tuple)):
            elements = list(arg.elts)
        elif isinstance(arg, ast.Name):
            bound = env.bindings.get(arg.id)
            if isinstance(bound, (ast.List, ast.Tuple)):
                elements = list(bound.elts) + env.appends.get(arg.id, [])
        if elements is None:
            out.add_dynamic()
            return out
        for i, element in enumerate(elements):
            if i:
                out.add_literal(sep)
            out.merge(self._resolve_fragment(element, cls_attrs, env, depth))
        return out

    # ---- walk ----
    def run(self) -> dict:
        self._scan_body(self.tree.body
                        if isinstance(self.tree, ast.Module) else [],
                        cls_attrs={}, cls_name="")
        return {"statements": self.statements, "classes": self.classes}

    def _scan_body(self, body, cls_attrs: dict, cls_name: str) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                attrs = _class_str_attrs(node)
                table = attrs.get("table")
                if isinstance(table, str) and table:
                    columns = attrs.get("columns")
                    self.classes.append({
                        "class": node.name, "line": node.lineno,
                        "table": table,
                        "columns": columns
                        if isinstance(columns, list) else None,
                    })
                self._scan_body(node.body, attrs, node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, cls_attrs, cls_name)
            else:
                self._scan_calls(node, cls_attrs, _FunctionEnv(node),
                                 cls_name, "")

    def _scan_function(self, func, cls_attrs: dict, cls_name: str) -> None:
        env = _FunctionEnv(func)
        for stmt in func.body:
            self._scan_calls(stmt, cls_attrs, env, cls_name, func.name)

    def _scan_calls(self, node, cls_attrs: dict, env: _FunctionEnv,
                    cls_name: str, func_name: str) -> None:
        for child in ast.walk(node):
            if not (isinstance(child, ast.Call)
                    and isinstance(child.func, ast.Attribute)
                    and child.func.attr in _EXEC_METHODS
                    and child.args
                    and _receiver_tail(child.func.value) in _DB_RECEIVERS):
                continue
            resolved = self._resolve(child.args[0], cls_attrs, env)
            if not resolved.text and not resolved.dynamic:
                continue        # not a string expression at all
            via = ".".join(p for p in (cls_name, func_name) if p)
            self.statements.append({
                "text": resolved.text,
                "literal": resolved.literal,
                "line": child.lineno,
                "seams": resolved.seams,
                "dynamic": resolved.dynamic,
                "via": via,
            })


def extract_sql_facts(tree: ast.AST, rel: str) -> dict:
    """{statements: [...], classes: [...]} for one parsed file — JSON-plain
    so the per-file fact cache round-trips it."""
    return _SqlExtractor(tree, rel).run()
