"""ko-analyze report model + rule registry.

A Finding is one defect at one location; a Report is an ordered, counted,
machine-readable collection of them. The RULES registry is the single place
a rule id, its severity, and its one-line contract live — `koctl lint`
renders it for --help, docs/analysis.md documents it, and the engines
(artifacts.py / astcheck.py) attach findings to it. Adding a rule without
registering it here is itself an internal error (the engines refuse unknown
ids), so the docs can never silently lag the checker.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubeoperator_tpu.version import __version__

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class RuleSpec:
    """One registered rule: identity + default severity + contract."""

    id: str            # stable id, e.g. "KO-X001"
    name: str          # short slug, e.g. "role-resolution"
    kind: str          # "artifact" | "ast"
    severity: str      # default severity of its findings
    summary: str       # one line: what must hold


# ---------------------------------------------------------------- registry --
RULES: dict[str, RuleSpec] = {
    spec.id: spec
    for spec in (
        # ---- cross-artifact rules (artifacts.py) ----
        RuleSpec(
            "KO-X001", "role-resolution", "artifact", ERROR,
            "every role a playbook or cross-role include references exists "
            "under content/roles/ with a tasks/main.yml",
        ),
        RuleSpec(
            "KO-X002", "file-resolution", "artifact", ERROR,
            "every template/copy/script src: in role tasks resolves inside "
            "the role's templates/ or files/ dir (node-absolute paths and "
            "runtime-computed sources are exempt; literal candidates inside "
            "jinja conditionals are each checked)",
        ),
        RuleSpec(
            "KO-X003", "phase-playbook", "artifact", ERROR,
            "every playbook the adm phase lists and the component catalog "
            "name exists under content/playbooks/ and parses as a list of "
            "plays with hosts",
        ),
        RuleSpec(
            "KO-X004", "plan-topology", "artifact", ERROR,
            "TPU plans and every catalog slice shape are topology-consistent "
            "(mesh axis product == slice chip count, derived host math, "
            "provider capability: accelerator=tpu requires gcp_tpu_vm)",
        ),
        RuleSpec(
            "KO-X005", "image-pin", "artifact", ERROR,
            "every container image a content template renders is declared in "
            "the offline bundle image contract (registry/manifest.py "
            "TEMPLATED_IMAGES) with the tag var the contract pins, and its "
            "tarball is listed in the bundle manifest",
        ),
        RuleSpec(
            "KO-X006", "migration-order", "artifact", ERROR,
            "SQL migrations under repository/migrations/ are named "
            "NNN_slug.sql, numbered strictly sequentially from 001 with no "
            "gaps or duplicates, and every statement is complete SQL",
        ),
        RuleSpec(
            "KO-X007", "manifest-ref", "artifact", ERROR,
            "every /opt/ko-manifests/<file> a role applies is listed in "
            "BUNDLED_MANIFESTS, and every generated manifest is bundled",
        ),
        RuleSpec(
            "KO-X008", "version-var", "artifact", ERROR,
            "every *_version jinja var content consumes is supplied by the "
            "extra-vars contract (COMPONENT_VERSIONS pins, TPU topology "
            "vars, k8s_version) or carries an inline | default()",
        ),
        RuleSpec(
            "KO-X011", "dag-contract", "artifact", ERROR,
            "every adm phase family is a valid dependency DAG: each "
            "Phase.after edge resolves to an earlier-declared phase in the "
            "SAME family (backward edges make the graph acyclic and keep "
            "declaration order a valid serial schedule), names are unique, "
            "and the ready-order is therefore deterministic",
        ),
        RuleSpec(
            "KO-X012", "multislice-launch", "artifact", ERROR,
            "a plan declaring num_slices > 1 requires the JobSet launch "
            "contract: a kind: JobSet template exists, a role task "
            "references it, and it wires MEGASCALE_COORDINATOR_ADDRESS — "
            "every existing JobSet template is held to the megascale-var "
            "requirement regardless of plans",
        ),
        # ---- project-rule AST checks (astcheck.py) ----
        RuleSpec(
            "KO-P001", "repo-layering", "ast", ERROR,
            "DB access only through the repository layer: sqlite3 is "
            "imported/used nowhere outside kubeoperator_tpu/repository/",
        ),
        RuleSpec(
            "KO-P002", "blocking-handler", "ast", ERROR,
            "no blocking call (time.sleep, subprocess.*, requests.*, "
            "os.system) lexically inside an async function — API handlers "
            "must off-load sync work via run_sync (sync closures defined "
            "inside the handler are exempt: they run on the executor)",
        ),
        RuleSpec(
            "KO-P004", "mutable-default", "ast", ERROR,
            "no mutable default argument (list/dict/set literal or "
            "constructor) on any function — shared-instance aliasing bugs",
        ),
        RuleSpec(
            "KO-P005", "bare-except", "ast", WARNING,
            "no bare `except:` handler — it swallows KeyboardInterrupt and "
            "SystemExit; catch Exception (or narrower) instead",
        ),
        RuleSpec(
            "KO-P006", "subprocess-timeout", "ast", ERROR,
            "every subprocess.run/Popen/check_* call outside terminal/ "
            "passes timeout= (or carries a `# KO-P006: waived — <reason>` "
            "comment) — an un-deadlined child process wedges its caller",
        ),
        RuleSpec(
            "KO-P011", "atomic-write", "ast", ERROR,
            "checkpoint-persistence modules (any package checkpoint.py) "
            "route every durable write — open() in a write mode, "
            ".write_text/.write_bytes, file-form json.dump — through the "
            "tmp+rename atomic helper (functions named atomic_*), or "
            "carry a `# KO-P011: waived — <reason>` comment; a bare "
            "write re-opens the torn-checkpoint crash window",
        ),
        RuleSpec(
            "KO-P012", "event-discipline", "ast", ERROR,
            "bus-event writes (`.events.save`/`.events.save_many`) "
            "happen only inside observability/events.py — every "
            "state-transition writer routes through emit_event / the "
            "journal's event verbs, so each event commits in the same "
            "transaction as the state change it describes and a "
            "fenced-out writer cannot narrate state it no longer owns",
        ),
        RuleSpec(
            "KO-P013", "event-kind", "ast", ERROR,
            "every literal event kind reaching emit_event() resolves in "
            "the EventKind vocabulary (observability/events.py) — "
            "exactly, or under a declared *_PREFIX dotted family; a "
            "typo'd kind streams events no filter, story reducer, or "
            "dashboard ever selects (computed kinds pass — EventKind "
            "attributes are the sanctioned spelling)",
        ),
        RuleSpec(
            "KO-P015", "metric-name", "ast", ERROR,
            "every literal metric family name reaching the exposition "
            "registry (family/histogram/_fmt first argument) resolves in "
            "the METRIC_FAMILIES vocabulary (api/metrics.py) — exactly, "
            "or as a declared family plus a classic series suffix "
            "(_bucket/_sum/_count/_total); a typo'd family renders "
            "series no dashboard or golden test ever selects (computed "
            "names pass — they resolve from a member at runtime)",
        ),
        RuleSpec(
            "KO-P014", "thread-discipline", "ast", ERROR,
            "service-layer code never constructs a bare threading.Thread "
            "— concurrency rides the shared adm/pool.py BoundedPool, and "
            "the few legitimate non-pool threads funnel through "
            "utils/threads.spawn (named + daemonized), or carry a "
            "`# KO-P014: waived — <reason>` comment",
        ),
        RuleSpec(
            "KO-P007", "phase-write-discipline", "ast", ERROR,
            "in-flight ClusterPhaseStatus assignments (Provisioning/"
            "Deploying/Scaling/Upgrading/Terminating) happen only in adm/ "
            "and resilience/journal.py — phase flips must ride the "
            "journaled path so a controller crash always leaves a "
            "sweepable operation record",
        ),
        # ---- project-wide flow rules (flow.py, over index.py facts) ----
        RuleSpec(
            "KO-P008", "guarded-by", "flow", ERROR,
            "each attribute's lock set is inferred from its write sites "
            "project-wide (lock context propagates through self-calls and "
            "inheritance); an attribute guarded at one write site must "
            "not be written bare at another — supersedes the retired "
            "single-file KO-P003 heuristic",
        ),
        RuleSpec(
            "KO-P009", "exception-flow", "flow", ERROR,
            "a journal open() owned by a function must reach close()/"
            "interrupt() on every normally-completing path (exception "
            "propagation is the sanctioned reraise), and no handler "
            "catching BaseException may swallow it — chaos "
            "ControllerDeath must tear through like a real SIGKILL",
        ),
        RuleSpec(
            "KO-P010", "span-discipline", "flow", ERROR,
            "every tracer.start_span() result reaches end_span() on all "
            "normally-completing paths (exception exits leave the span "
            "Running as crash evidence, like an open journal op), and "
            "the tracer.span(...) context-manager form is actually used "
            "in a `with` — a leaked span reads Running forever and "
            "corrupts the duration histograms",
        ),
        # ---- SQL rules (sqlrules.py, over the sqlmodel substrate) ----
        RuleSpec(
            "KO-S001", "schema-conformance", "sql", ERROR,
            "every table/column a resolved SQL statement references — and "
            "every repo-class mirror-column declaration — exists in the "
            "schema model folded from migrations 001..NNN; typos and "
            "queries against never-created columns fail the gate",
        ),
        RuleSpec(
            "KO-S002", "dialect-portability", "sql", ERROR,
            "no SQLite-only construct (julianday/datetime/strftime, "
            "INSERT OR REPLACE/IGNORE, PRAGMA, bare rowid) outside the "
            "sanctioned seams: the DB_NOW_SQL clock seam, the ROWID_SQL "
            "stream-cursor seam, PRAGMAs inside repository/db.py — "
            "anything else must be ANSI-ish or carry a waiver naming its "
            "Postgres translation",
        ),
        RuleSpec(
            "KO-S003", "index-coverage", "sql", ERROR,
            "positive filter predicates on the hot mirrored-column tables "
            "(operations, events, workload_queue, metric_samples) are "
            "led by a declared index — an unindexed scan on a bus-scale "
            "table is a perf regression, not a style nit",
        ),
        RuleSpec(
            "KO-S004", "migration-discipline", "sql", ERROR,
            "migrations are strictly additive DDL (CREATE TABLE, CREATE "
            "INDEX, ALTER TABLE ADD COLUMN only) and nothing references "
            "a table or column before the migration that creates it",
        ),
        # ---- contract rules (contracts.py, over index.py facts) ----
        RuleSpec(
            "KO-X009", "config-contract", "contract", ERROR,
            "every literal config.get() key resolves in utils/config.py "
            "DEFAULTS, every DEFAULTS leaf is read somewhere, and docs "
            "knob tables match (resilience/chaos/watchdog blocks fully "
            "documented)",
        ),
        RuleSpec(
            "KO-X010", "surface-parity", "contract", ERROR,
            "every koctl REST call resolves to a registered api/server.py "
            "route AND a LocalClient dispatch case, every local dispatch "
            "case shadows a real route, and every top-level koctl command "
            "is documented",
        ),
    )
}


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str          # rule id from RULES
    file: str          # path relative to the analysis root's parent
    line: int          # 1-based; 0 = whole-file/whole-artifact finding
    message: str
    severity: str = ""  # defaults to the rule's registered severity
    waived: str = ""    # waiver justification; non-empty = suppressed

    def __post_init__(self) -> None:
        if self.rule not in RULES:
            raise ValueError(f"finding references unregistered rule {self.rule}")
        if not self.severity:
            object.__setattr__(self, "severity", RULES[self.rule].severity)

    def to_dict(self) -> dict:
        out = {
            "rule": self.rule,
            "name": RULES[self.rule].name,
            "severity": self.severity,
            "file": self.file,
            "line": self.line,
            "message": self.message,
        }
        if self.waived:
            out["waived"] = self.waived
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        """Inverse of to_dict minus the derived `name` — the incremental
        cache round-trips findings through JSON."""
        return cls(rule=d["rule"], file=d["file"], line=d["line"],
                   message=d["message"], severity=d["severity"],
                   waived=d.get("waived", ""))


@dataclass
class Report:
    """The analyzer's output: findings + run metadata, JSON-stable."""

    root: str
    findings: list[Finding] = field(default_factory=list)
    rules_run: list[str] = field(default_factory=list)
    runtime_s: float = 0.0
    files_scanned: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    unused_waivers: list[str] = field(default_factory=list)

    def extend(self, findings: list[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> list[Finding]:
        """Error-severity findings that are NOT waived — the exit-code
        set. Waived findings stay visible but never fail the gate."""
        return [f for f in self.findings
                if f.severity == ERROR and not f.waived]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings
                if f.severity == WARNING and not f.waived]

    @property
    def waived(self) -> list[Finding]:
        return [f for f in self.findings if f.waived]

    def exit_code(self) -> int:
        """Tooling contract: 0 clean, 1 error findings (warnings alone stay
        0 so advisory rules can land before their fixes do), 2 is reserved
        for internal analyzer failure and raised by the CLI wrapper."""
        return 1 if self.errors else 0

    def sorted_findings(self) -> list[Finding]:
        return sorted(
            self.findings, key=lambda f: (f.file, f.line, f.rule, f.message)
        )

    def to_dict(self) -> dict:
        return {
            "analyzer": "ko-analyze",
            "version": __version__,
            "root": self.root,
            "rules_run": sorted(self.rules_run),
            "files_scanned": self.files_scanned,
            "runtime_s": round(self.runtime_s, 3),
            "counts": {
                "error": len(self.errors),
                "warning": len(self.warnings),
                "waived": len(self.waived),
            },
            "unused_waivers": list(self.unused_waivers),
            "findings": [f.to_dict() for f in self.sorted_findings()],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self) -> str:
        """Human-readable finding list + one-line summary (koctl default)."""
        lines = []
        for f in self.sorted_findings():
            where = f"{f.file}:{f.line}" if f.line else f.file
            tag = "WAIVED " if f.waived else f"{f.severity.upper():7s}"
            lines.append(
                f"{tag} {f.rule} [{RULES[f.rule].name}] "
                f"{where}: {f.message}"
                + (f" [waived: {f.waived}]" if f.waived else "")
            )
        for desc in self.unused_waivers:
            lines.append(f"STALE   waiver matches nothing: {desc}")
        waived = f", {len(self.waived)} waived" if self.waived else ""
        cache = (f", cache {self.cache_hits}h/{self.cache_misses}m"
                 if self.cache_hits or self.cache_misses else "")
        lines.append(
            f"ko-analyze: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s){waived} across "
            f"{len(self.rules_run)} rules, {self.files_scanned} files "
            f"({self.runtime_s:.2f}s{cache})"
        )
        return "\n".join(lines)
