"""ko-analyze report model + rule registry.

A Finding is one defect at one location; a Report is an ordered, counted,
machine-readable collection of them. The RULES registry is the single place
a rule id, its severity, and its one-line contract live — `koctl lint`
renders it for --help, docs/analysis.md documents it, and the engines
(artifacts.py / astcheck.py) attach findings to it. Adding a rule without
registering it here is itself an internal error (the engines refuse unknown
ids), so the docs can never silently lag the checker.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from kubeoperator_tpu.version import __version__

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class RuleSpec:
    """One registered rule: identity + default severity + contract."""

    id: str            # stable id, e.g. "KO-X001"
    name: str          # short slug, e.g. "role-resolution"
    kind: str          # "artifact" | "ast"
    severity: str      # default severity of its findings
    summary: str       # one line: what must hold


# ---------------------------------------------------------------- registry --
RULES: dict[str, RuleSpec] = {
    spec.id: spec
    for spec in (
        # ---- cross-artifact rules (artifacts.py) ----
        RuleSpec(
            "KO-X001", "role-resolution", "artifact", ERROR,
            "every role a playbook or cross-role include references exists "
            "under content/roles/ with a tasks/main.yml",
        ),
        RuleSpec(
            "KO-X002", "file-resolution", "artifact", ERROR,
            "every template/copy/script src: in role tasks resolves inside "
            "the role's templates/ or files/ dir (node-absolute paths and "
            "runtime-computed sources are exempt; literal candidates inside "
            "jinja conditionals are each checked)",
        ),
        RuleSpec(
            "KO-X003", "phase-playbook", "artifact", ERROR,
            "every playbook the adm phase lists and the component catalog "
            "name exists under content/playbooks/ and parses as a list of "
            "plays with hosts",
        ),
        RuleSpec(
            "KO-X004", "plan-topology", "artifact", ERROR,
            "TPU plans and every catalog slice shape are topology-consistent "
            "(mesh axis product == slice chip count, derived host math, "
            "provider capability: accelerator=tpu requires gcp_tpu_vm)",
        ),
        RuleSpec(
            "KO-X005", "image-pin", "artifact", ERROR,
            "every container image a content template renders is declared in "
            "the offline bundle image contract (registry/manifest.py "
            "TEMPLATED_IMAGES) with the tag var the contract pins, and its "
            "tarball is listed in the bundle manifest",
        ),
        RuleSpec(
            "KO-X006", "migration-order", "artifact", ERROR,
            "SQL migrations under repository/migrations/ are named "
            "NNN_slug.sql, numbered strictly sequentially from 001 with no "
            "gaps or duplicates, and every statement is complete SQL",
        ),
        RuleSpec(
            "KO-X007", "manifest-ref", "artifact", ERROR,
            "every /opt/ko-manifests/<file> a role applies is listed in "
            "BUNDLED_MANIFESTS, and every generated manifest is bundled",
        ),
        RuleSpec(
            "KO-X008", "version-var", "artifact", ERROR,
            "every *_version jinja var content consumes is supplied by the "
            "extra-vars contract (COMPONENT_VERSIONS pins, TPU topology "
            "vars, k8s_version) or carries an inline | default()",
        ),
        # ---- project-rule AST checks (astcheck.py) ----
        RuleSpec(
            "KO-P001", "repo-layering", "ast", ERROR,
            "DB access only through the repository layer: sqlite3 is "
            "imported/used nowhere outside kubeoperator_tpu/repository/",
        ),
        RuleSpec(
            "KO-P002", "blocking-handler", "ast", ERROR,
            "no blocking call (time.sleep, subprocess.*, requests.*, "
            "os.system) lexically inside an async function — API handlers "
            "must off-load sync work via run_sync (sync closures defined "
            "inside the handler are exempt: they run on the executor)",
        ),
        RuleSpec(
            "KO-P003", "lock-discipline", "ast", ERROR,
            "a self attribute written inside a `with self.<lock>:` block in "
            "one method must not also be written outside any lock in "
            "another (a lightweight write-write race detector; __init__ and "
            "*_locked helper methods are exempt by convention)",
        ),
        RuleSpec(
            "KO-P004", "mutable-default", "ast", ERROR,
            "no mutable default argument (list/dict/set literal or "
            "constructor) on any function — shared-instance aliasing bugs",
        ),
        RuleSpec(
            "KO-P005", "bare-except", "ast", WARNING,
            "no bare `except:` handler — it swallows KeyboardInterrupt and "
            "SystemExit; catch Exception (or narrower) instead",
        ),
        RuleSpec(
            "KO-P006", "subprocess-timeout", "ast", ERROR,
            "every subprocess.run/Popen/check_* call outside terminal/ "
            "passes timeout= (or carries a `# KO-P006: waived — <reason>` "
            "comment) — an un-deadlined child process wedges its caller",
        ),
        RuleSpec(
            "KO-P007", "phase-write-discipline", "ast", ERROR,
            "in-flight ClusterPhaseStatus assignments (Provisioning/"
            "Deploying/Scaling/Upgrading/Terminating) happen only in adm/ "
            "and resilience/journal.py — phase flips must ride the "
            "journaled path so a controller crash always leaves a "
            "sweepable operation record",
        ),
    )
}


@dataclass(frozen=True)
class Finding:
    """One rule violation at one location."""

    rule: str          # rule id from RULES
    file: str          # path relative to the analysis root's parent
    line: int          # 1-based; 0 = whole-file/whole-artifact finding
    message: str
    severity: str = ""  # defaults to the rule's registered severity

    def __post_init__(self) -> None:
        if self.rule not in RULES:
            raise ValueError(f"finding references unregistered rule {self.rule}")
        if not self.severity:
            object.__setattr__(self, "severity", RULES[self.rule].severity)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "name": RULES[self.rule].name,
            "severity": self.severity,
            "file": self.file,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Report:
    """The analyzer's output: findings + run metadata, JSON-stable."""

    root: str
    findings: list[Finding] = field(default_factory=list)
    rules_run: list[str] = field(default_factory=list)
    runtime_s: float = 0.0
    files_scanned: int = 0

    def extend(self, findings: list[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def warnings(self) -> list[Finding]:
        return [f for f in self.findings if f.severity == WARNING]

    def exit_code(self) -> int:
        """Tooling contract: 0 clean, 1 error findings (warnings alone stay
        0 so advisory rules can land before their fixes do), 2 is reserved
        for internal analyzer failure and raised by the CLI wrapper."""
        return 1 if self.errors else 0

    def sorted_findings(self) -> list[Finding]:
        return sorted(
            self.findings, key=lambda f: (f.file, f.line, f.rule, f.message)
        )

    def to_dict(self) -> dict:
        return {
            "analyzer": "ko-analyze",
            "version": __version__,
            "root": self.root,
            "rules_run": sorted(self.rules_run),
            "files_scanned": self.files_scanned,
            "runtime_s": round(self.runtime_s, 3),
            "counts": {
                "error": len(self.errors),
                "warning": len(self.warnings),
            },
            "findings": [f.to_dict() for f in self.sorted_findings()],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def render_text(self) -> str:
        """Human-readable finding list + one-line summary (koctl default)."""
        lines = []
        for f in self.sorted_findings():
            where = f"{f.file}:{f.line}" if f.line else f.file
            lines.append(
                f"{f.severity.upper():7s} {f.rule} [{RULES[f.rule].name}] "
                f"{where}: {f.message}"
            )
        lines.append(
            f"ko-analyze: {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s) across "
            f"{len(self.rules_run)} rules, {self.files_scanned} files "
            f"({self.runtime_s:.2f}s)"
        )
        return "\n".join(lines)
