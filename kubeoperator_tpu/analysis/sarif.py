"""SARIF 2.1.0 serialization + the checked-in waiver/baseline file.

SARIF is the interchange format CI annotators and editors consume
(github code-scanning, VS Code SARIF viewer); emitting it makes the gate's
findings land as PR annotations instead of a log to grep. One run, one
tool (`ko-analyze`), every registered rule in the driver's rule table so
`ruleIndex` references resolve.

Waivers are the baseline mechanism that lets a warning-tier rule land at
ERROR severity before the tree is fully clean: a finding matched by a
waiver keeps its text but stops counting toward the exit code, and SARIF
carries it as a suppressed result with the waiver's justification. Every
waiver MUST have a reason — an unexplained suppression is how baselines
rot. Unused waivers are reported so stale entries get deleted.
"""

from __future__ import annotations

import fnmatch
import json
import os
from dataclasses import dataclass

import yaml

from kubeoperator_tpu.analysis.report import ERROR, RULES, WARNING, Finding
from kubeoperator_tpu.version import __version__

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"

_LEVELS = {ERROR: "error", WARNING: "warning"}


# ------------------------------------------------------------------ waivers --
@dataclass(frozen=True)
class Waiver:
    """One baseline entry: which findings it suppresses and WHY."""

    rule: str
    reason: str
    file: str = ""        # fnmatch pattern over the finding's rel path
    contains: str = ""    # substring of the finding's message

    def matches(self, finding: Finding) -> bool:
        if finding.rule != self.rule:
            return False
        if self.file and not fnmatch.fnmatch(
                finding.file.replace(os.sep, "/"), self.file):
            return False
        if self.contains and self.contains not in finding.message:
            return False
        return True


def load_waivers(path: str) -> list:
    """Parse the waiver file. Malformed entries raise — a waiver that
    silently fails to parse would un-suppress (or worse, a future format
    drift could over-suppress); the CLI maps the raise to exit 2."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f) or {}
    waivers: list = []
    for i, entry in enumerate(doc.get("waivers", [])):
        if not isinstance(entry, dict):
            raise ValueError(f"waiver #{i} is not a mapping")
        rule = entry.get("rule", "")
        reason = str(entry.get("reason", "")).strip()
        if rule not in RULES:
            raise ValueError(f"waiver #{i} names unknown rule {rule!r}")
        if not reason:
            raise ValueError(
                f"waiver #{i} ({rule}) has no reason — every suppression "
                f"must carry its justification in-repo")
        if rule == "KO-S002" and "postgres" not in reason.lower():
            raise ValueError(
                f"waiver #{i} (KO-S002) must name the Postgres "
                f"translation of the waived SQLite-ism in its reason — "
                f"a dialect waiver without a migration plan is how the "
                f"Postgres seam rots")
        waivers.append(Waiver(rule=rule, reason=reason,
                              file=str(entry.get("file", "")),
                              contains=str(entry.get("contains", ""))))
    return waivers


def apply_waivers(findings: list, waivers: list) -> tuple:
    """Returns (findings-with-waived-marked, unused_waivers). Matching
    findings get their `waived` reason set (Report then excludes them from
    the exit code); Waiver objects that matched nothing are returned so
    the caller can report the ones whose rule actually ran as stale."""
    used: set = set()
    out: list = []
    for finding in findings:
        waived_by = next((w for w in waivers if w.matches(finding)), None)
        if waived_by is not None:
            used.add(waived_by)
            finding = Finding(
                rule=finding.rule, file=finding.file, line=finding.line,
                message=finding.message, severity=finding.severity,
                waived=waived_by.reason,
            )
        out.append(finding)
    return out, [w for w in waivers if w not in used]


# ------------------------------------------------------------------- SARIF --
def to_sarif(report) -> dict:
    """Render a Report as a SARIF 2.1.0 log (dict; json.dumps-ready)."""
    rule_ids = sorted(RULES)
    rule_index = {rid: i for i, rid in enumerate(rule_ids)}
    results = []
    for finding in report.sorted_findings():
        result = {
            "ruleId": finding.rule,
            "ruleIndex": rule_index[finding.rule],
            "level": "note" if finding.waived
                     else _LEVELS.get(finding.severity, "warning"),
            "message": {"text": finding.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": finding.file.replace(os.sep, "/"),
                        "uriBaseId": "SRCROOT",
                    },
                    # line 0 means whole-artifact: SARIF regions are
                    # 1-based, so omit the region entirely there
                    **({"region": {"startLine": finding.line}}
                       if finding.line else {}),
                },
            }],
        }
        if finding.waived:
            result["suppressions"] = [{
                "kind": "external",
                "justification": finding.waived,
            }]
        results.append(result)
    src_root = os.path.dirname(os.path.abspath(report.root))
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "ko-analyze",
                "version": __version__,
                "informationUri":
                    "https://github.com/ghl1024/KubeOperator",
                "rules": [{
                    "id": rid,
                    "name": RULES[rid].name,
                    "shortDescription": {"text": RULES[rid].summary},
                    "defaultConfiguration": {
                        "level": _LEVELS.get(RULES[rid].severity,
                                             "warning"),
                    },
                } for rid in rule_ids],
            }},
            "originalUriBaseIds": {
                "SRCROOT": {"uri": "file://" + src_root.rstrip("/") + "/"},
            },
            "invocations": [{
                "executionSuccessful": True,
                "exitCode": report.exit_code(),
            }],
            "results": results,
        }],
    }


def to_sarif_json(report) -> str:
    return json.dumps(to_sarif(report), indent=2)
