"""Hand-scheduled pallas diagnostics: explicit-DMA HBM reads and an ICI
ring all-gather over remote DMA.

The XLA-level benches (collectives.py, hbm.py) measure what the compiler's
schedule achieves; these two kernels measure what the raw engines achieve
when driven directly (/opt/skills/guides/pallas_guide.md patterns 17/18):

  dma_read_bandwidth_gbps  double-buffered `make_async_copy` HBM→VMEM
                           stream — isolates the DMA engines from XLA's
                           fusion choices; a gap vs hbm.py's triad points
                           at scheduling, a gap vs datasheet at memory.
  ring_all_gather          neighbor-to-neighbor `make_async_remote_copy`
                           ring — the ICI-health analog: XLA's all_gather
                           may route differently; the explicit ring pins
                           traffic to adjacent links, so a slow link shows
                           up instead of being averaged away.

Both run `interpret=True` on CPU so CI exercises the identical kernel code
(multi-device interpret emulates the remote DMAs on the host mesh).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeoperator_tpu.ops.collectives import CollectiveResult
from kubeoperator_tpu.ops.timing import differential_time_per_iter
from kubeoperator_tpu.parallel.mesh import flat_axis_mesh, shard_map_compat

AXIS = "devices"
COLS = 1024        # lane-aligned
CHUNK_ROWS = 256   # f32 tile-aligned (multiple of 8)

# jax renamed TPUCompilerParams -> CompilerParams across the versions this
# image family ships; resolve once so the collective kernels build on both
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) \
    or getattr(pltpu, "TPUCompilerParams")


def _axis_size(name: str):
    """jax.lax.axis_size is newer than this image family's oldest jax;
    psum(1, axis) is the portable spelling of the same value."""
    size = getattr(jax.lax, "axis_size", None)
    return size(name) if size is not None else jax.lax.psum(1, name)


# ------------------------------------------------------------ DMA stream ----
def _dma_read_kernel(seed_ref, hbm_ref, out_ref):
    """Sum `hbm_ref` chunk-wise, double-buffering HBM→VMEM copies so the
    next chunk's DMA overlaps the current chunk's reduction."""
    num_chunks = hbm_ref.shape[0] // CHUNK_ROWS

    def body(scratch, sem):
        def get_dma(slot, idx):
            return pltpu.make_async_copy(
                hbm_ref.at[pl.ds(idx * CHUNK_ROWS, CHUNK_ROWS)],
                scratch.at[slot],
                sem.at[slot],
            )

        get_dma(0, 0).start()

        def loop(idx, acc):
            cur = jax.lax.rem(idx, 2)
            nxt = jax.lax.rem(idx + 1, 2)

            @pl.when(idx + 1 < num_chunks)
            def _():
                get_dma(nxt, idx + 1).start()

            get_dma(cur, idx).wait()
            return acc + scratch[cur].reshape(-1, 8, COLS).sum(axis=0)

        # seed varies per bench iteration so chained calls can never be
        # collapsed into one by the compiler
        acc0 = jnp.full((8, COLS), seed_ref[0], jnp.float32)
        out_ref[...] = jax.lax.fori_loop(0, num_chunks, loop, acc0)

    pl.run_scoped(
        body,
        scratch=pltpu.VMEM((2, CHUNK_ROWS, COLS), jnp.float32),
        sem=pltpu.SemaphoreType.DMA((2,)),
    )


def _dma_read(x, seed, interpret: bool):
    return pl.pallas_call(
        _dma_read_kernel,
        out_shape=jax.ShapeDtypeStruct((8, COLS), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),  # stays in HBM; DMA'd manually
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(seed, x)


@dataclass(frozen=True)
class DmaReadResult:
    bytes_read: int
    time_s: float
    gbps: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def dma_read_bandwidth_gbps(
    size_mb: float = 256.0, iters: int = 20, device: jax.Device | None = None
) -> DmaReadResult:
    """Sustained HBM read bandwidth through explicit double-buffered DMA."""
    device = device or jax.devices()[0]
    interpret = device.platform != "tpu"
    if interpret:
        size_mb = min(size_mb, 1.0)  # interpreter is slow; CI only
        iters = min(iters, 2)
    else:
        # a sub-10ms window behind the TPU relay reads above datasheet —
        # keep device time in the 100ms range so RTT jitter cancels
        iters = max(iters, 300)
    rows = max(int(size_mb * 1e6) // (COLS * 4) // CHUNK_ROWS, 1) * CHUNK_ROWS
    x = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)

    @partial(jax.jit, static_argnums=(1,))
    def chain(v, n):
        def step(i, acc):
            seed = jnp.full((1,), i, jnp.float32)
            return acc + _dma_read(v, seed, interpret)[0, 0]
        return jax.lax.fori_loop(0, n, step, jnp.float32(0))

    def run(n: int) -> float:
        return float(chain(x, n))

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2)
    )
    bytes_read = rows * COLS * 4
    return DmaReadResult(
        bytes_read=bytes_read, time_s=dt, gbps=bytes_read / dt / 1e9
    )


# ------------------------------------------------------- ICI ring gather ----
def _ring_all_gather_kernel(local_ref, out_ref, comm_ref, send_sem, recv_sem,
                            ready_sem, *, flow_control: bool):
    """Each step: pass the chunk received last step to the right neighbor
    while copying it into the local output (bidirectional-ring upgrade is a
    follow-up; one direction already pins traffic to adjacent ICI links).

    Flow control: my right neighbor writes into MY comm slots, so after
    finishing step i (send drained, received chunk consumed) each device
    signals its LEFT neighbor, and before the step-i send (i>0) waits for
    its RIGHT neighbor's step-(i-1) signal. Without it a fast device's
    step-i remote write can clobber the slot a lagging neighbor is still
    reading for its step-(i-1) send — a skew exactly as large as the slow
    ICI link this diagnostic exists to expose. `flow_control` is False only
    under interpret mode (lockstep emulation; remote semaphore_signal is
    not implemented there)."""
    ndev = _axis_size(AXIS)
    my_id = jax.lax.axis_index(AXIS)
    chunk = local_ref.shape[0]

    out_ref[pl.ds(my_id * chunk, chunk), :] = local_ref[...]
    comm_ref[0] = local_ref[...]

    def step(i, _):
        send_slot = jax.lax.rem(i, 2)
        recv_slot = jax.lax.rem(i + 1, 2)
        dst = jax.lax.rem(my_id + 1, ndev)
        left = jax.lax.rem(my_id - 1 + ndev, ndev)

        if flow_control:
            @pl.when(i > 0)
            def _():
                pltpu.semaphore_wait(ready_sem, 1)

        rdma = pltpu.make_async_remote_copy(
            src_ref=comm_ref.at[send_slot],
            dst_ref=comm_ref.at[recv_slot],
            send_sem=send_sem.at[send_slot],
            recv_sem=recv_sem.at[recv_slot],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        rdma.start()
        rdma.wait()
        src_dev = jax.lax.rem(my_id - i - 1 + ndev, ndev)
        out_ref[pl.ds(src_dev * chunk, chunk), :] = comm_ref[recv_slot]
        if flow_control:
            # the last step's signal has no matching wait (the neighbor's
            # loop is over) — skip it so ready_sem is drained at kernel
            # exit, as Mosaic requires of scratch semaphores
            @pl.when(i < ndev - 2)
            def _():
                pltpu.semaphore_signal(
                    ready_sem, inc=1, device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
        return 0

    jax.lax.fori_loop(0, ndev - 1, step, 0)


def ring_all_gather(x, mesh=None, interpret: bool | None = None):
    """All-gather a row-sharded [n*chunk, COLS] array via an explicit ICI
    ring. Returns the fully-gathered array (replicated)."""
    mesh = mesh or flat_axis_mesh()
    n = mesh.devices.size
    if interpret is None:
        interpret = mesh.devices.flat[0].platform != "tpu"
    rows, cols = x.shape
    if rows % n:
        raise ValueError(f"rows {rows} not divisible by {n} devices")
    chunk = rows // n

    def gather(v):
        return pl.pallas_call(
            partial(_ring_all_gather_kernel, flow_control=not interpret),
            out_shape=jax.ShapeDtypeStruct((rows, cols), x.dtype),
            scratch_shapes=[
                pltpu.VMEM((2, chunk, cols), x.dtype),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS(collective_id=0),
        )(v)

    x = jax.device_put(x, NamedSharding(mesh, P(AXIS, None)))
    return jax.jit(
        shard_map_compat(gather, mesh=mesh, in_specs=P(AXIS, None),
                         out_specs=P(None, None))
    )(x)


def bench_ring_all_gather(
    size_mb: float = 16.0, mesh=None, iters: int = 10
) -> CollectiveResult:
    """Bus bandwidth of the explicit ring (nccl-tests all_gather convention:
    busbw = (n-1) * shard_bytes / t)."""
    mesh = mesh or flat_axis_mesh()
    n = mesh.devices.size
    interpret = mesh.devices.flat[0].platform != "tpu"
    if interpret:
        size_mb = min(size_mb, 0.5)
        iters = min(iters, 2)
    shard_rows = max(int(size_mb * 1e6) // (COLS * 4) // 8, 1) * 8
    rows = shard_rows * n
    x = jax.device_put(
        jnp.ones((rows, COLS), jnp.float32),
        NamedSharding(mesh, P(AXIS, None)),
    )
    chunk = shard_rows

    def gather(v):
        return pl.pallas_call(
            partial(_ring_all_gather_kernel, flow_control=not interpret),
            out_shape=jax.ShapeDtypeStruct((rows, COLS), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((2, chunk, COLS), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.DMA((2,)),
                pltpu.SemaphoreType.REGULAR,
            ],
            interpret=interpret,
            compiler_params=_COMPILER_PARAMS(collective_id=0),
        )(v)

    @partial(jax.jit, static_argnums=(1,))
    def run_iters(v, k):
        @partial(shard_map_compat, mesh=mesh, in_specs=P(AXIS, None),
                 out_specs=P(AXIS, None))
        def body(u):
            def step(_, w):
                g = gather(w)
                # keep only the local shard so iterations chain at shard size
                return jax.lax.dynamic_slice_in_dim(
                    g, jax.lax.axis_index(AXIS) * chunk, chunk
                ) * (1.0 / n)
            return jax.lax.fori_loop(0, k, step, u)

        return body(v).sum()

    def run(k: int) -> float:
        return float(run_iters(x, k))

    dt = differential_time_per_iter(
        run, lo=max(iters // 4, 1), hi=max(iters, iters // 4 + 2)
    )
    shard_bytes = chunk * COLS * 4
    algbw = shard_bytes / dt / 1e9
    return CollectiveResult(
        op="pallas_ring_all_gather", n_devices=n,
        bytes_per_device=shard_bytes, time_per_iter_s=dt,
        algbw_gbps=algbw, busbw_gbps=algbw * (n - 1),
    )


def verify_ring_all_gather(mesh=None) -> bool:
    """Correctness gate: explicit ring must agree with the XLA collective."""
    mesh = mesh or flat_axis_mesh()
    n = mesh.devices.size
    rows = 8 * n
    x = jnp.arange(rows * COLS, dtype=jnp.float32).reshape(rows, COLS)
    out = ring_all_gather(x, mesh)
    return bool(np.array_equal(np.asarray(out), np.asarray(x)))
