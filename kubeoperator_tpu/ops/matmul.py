"""MXU sustained-throughput check.

Per-chip systolic-array health probe: chained bf16 matmuls sized to the MXU
(multiples of 128x128, bf16 native input dtype), iterated inside one jit'd
`lax.fori_loop` so only device time is measured. The result is compared
against the generation's datasheet bf16 TFLOP/s to flag degraded chips —
the TPU analog of the per-GPU compute check NCCL-tests runs implicitly.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubeoperator_tpu.ops.timing import differential_time_per_iter


@dataclass(frozen=True)
class MatmulResult:
    size: int
    dtype: str
    iters: int
    time_s: float
    tflops: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def mxu_matmul_tflops(
    size: int = 4096,
    iters: int = 30,
    dtype=jnp.bfloat16,
    device: jax.Device | None = None,
) -> MatmulResult:
    """Sustained TFLOP/s of `iters` chained [size,size] matmuls on one device."""
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        # CPU CI / eyeballing hosts: keep it fast, same clamp discipline as
        # hbm.py / pallas_kernels.py — a 4096^2 x200 chain is minutes on CPU
        size = min(size, 512)
        iters = min(iters, 8)
    key = jax.random.PRNGKey(0)
    a = jax.device_put(
        jax.random.normal(key, (size, size), jnp.float32).astype(dtype), device
    )
    w = jax.device_put(
        jax.random.normal(key, (size, size), jnp.float32).astype(dtype), device
    )

    @partial(jax.jit, static_argnums=(2,))
    def chain(x, w, n):
        def step(_, v):
            # normalize cheaply to keep values finite; fuses into the matmul
            y = jnp.dot(v, w, preferred_element_type=jnp.float32)
            return (y * (1.0 / size)).astype(dtype)
        out = jax.lax.fori_loop(0, n, step, x)
        return out.astype(jnp.float32).sum()  # scalar readback proves completion

    def run(n: int) -> float:
        return float(chain(a, w, n))  # float() forces host fetch

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2), trials=5
    )
    flops = 2.0 * size * size * size
    return MatmulResult(
        size=size, dtype=jnp.dtype(dtype).name, iters=iters, time_s=dt,
        tflops=flops / dt / 1e12,
    )
