"""MXU sustained-throughput check.

Per-chip systolic-array health probe: chained bf16 matmuls sized to the MXU
(multiples of 128x128, bf16 native input dtype), iterated inside one jit'd
`lax.fori_loop` so only device time is measured. The result is compared
against the generation's datasheet bf16 TFLOP/s to flag degraded chips —
the TPU analog of the per-GPU compute check NCCL-tests runs implicitly.

MEASURED FINDINGS — the r4 "rerun droop" root cause (VERDICT r4 weak #1,
v5e single chip behind the axon tunnel, 2026-07-30):

* The 10.3% r4 in-run droop (193.2 -> 173.3) was NOT clock ramp-down,
  thermal throttling, or recompilation. Instrumented repeats show no
  monotonic decline across 8 back-to-back headline runs, and 10s
  cool-down pauses change nothing (cooled medians 173-177 == sustained
  back-to-back medians 176-182).
* The droop was ESTIMATOR NOISE: differential timing subtracts a short
  `lo` run from a long `hi` run, and the old span (lo=7, hi=60 at 8192)
  left only 53 delta-iterations (~330ms of device time) to absorb the
  tunnel's +-30ms per-call RTT jitter — a 9-18% per-trial band. Widening
  the span collapses the band with the median unmoved:
      (lo=7,  hi=60) : band 18.3%, median 175.3 TFLOP/s
      (lo=30, hi=150): band  5.1%, median 175.5
      (lo=60, hi=240): band  2.8%, median 174.4
* The old headline took MAX-of-draws over that fat-tailed distribution
  ("best-of-2 rerun"), which converges on the top of the noise band —
  at 8192 the honest sustained median is ~175 TFLOP/s (0.886 of the 197
  datasheet), rock-stable, NOT the 193 the max suggested.
* The size sweep, re-measured at honest spans, is a REAL effect though:
  4096 sustains ~193 median (0.98 of datasheet) because both operands
  (32MB bf16) stay VMEM-resident, while 8192's 128MB operands stream
  from HBM every iteration — so the per-shape ranking r4 reported was
  right even when its per-shape error bars were not. The headline is the
  sweep max OF MEDIANS, each with its band printed beside it.
* It is the chain, not slack, at 8192: folding the inter-matmul rescale
  into the weights and emitting bf16 straight from the MXU (no separate
  cast) measures the same 174.6 median — XLA already fuses the epilogue;
  the 8192 gap to datasheet is HBM streaming, not the normalization.

Protocol accordingly: lo=iters, hi=4*iters (>= 3*iters of differential
span), 7 trials, MEDIAN as the estimate, full min-max band reported so a
band blow-out (> ~5%, i.e. 2x the documented 2-4% tunnel variance) is
visible instead of silently inflating a max.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from functools import partial
from statistics import median

import jax
import jax.numpy as jnp


def _round_tflops(v: float) -> float:
    """1-decimal readability at TPU scale (hundreds of TFLOP/s), but keep
    sub-0.05 CPU-tier measurements nonzero: a loaded CI host's differential
    estimate can land below 0.05 TFLOP/s, and round(v, 1) == 0.0 would
    erase a real positive measurement."""
    return round(v, 1) if v >= 1.0 else round(v, 4)


@dataclass(frozen=True)
class MatmulResult:
    size: int
    dtype: str
    iters: int
    time_s: float
    tflops: float                      # median-of-trials estimate
    tflops_band: tuple = ()            # (min, max) across trials
    trials: tuple = ()                 # per-trial TFLOP/s draws

    @property
    def band_pct(self) -> float:
        """Band width as % of the median — > ~5% means the tunnel was
        unusually noisy during this run (2x the documented 2-4%)."""
        if not self.tflops_band or self.tflops <= 0:
            return 0.0
        lo, hi = self.tflops_band
        return (hi - lo) / self.tflops * 100.0

    def to_dict(self) -> dict:
        d = dict(self.__dict__)
        d["tflops_band"] = list(self.tflops_band)
        d["trials"] = list(self.trials)
        d["band_pct"] = round(self.band_pct, 1)
        return d


def mxu_matmul_tflops(
    size: int = 4096,
    iters: int = 30,
    dtype=jnp.bfloat16,
    device: jax.Device | None = None,
    trials: int = 7,
) -> MatmulResult:
    """Sustained TFLOP/s of chained [size,size] matmuls on one device.

    `iters` sets the differential span: lo=iters, hi=4*iters — see the
    module docstring for why the span must dwarf tunnel RTT jitter. The
    returned .tflops is the MEDIAN of `trials` differential draws."""
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        # CPU CI / eyeballing hosts: keep it fast, same clamp discipline as
        # hbm.py / pallas_kernels.py — a 4096^2 chain is minutes on CPU
        size = min(size, 512)
        iters = min(iters, 4)
        trials = min(trials, 3)
    key = jax.random.PRNGKey(0)
    a = jax.device_put(
        jax.random.normal(key, (size, size), jnp.float32).astype(dtype), device
    )
    w = jax.device_put(
        jax.random.normal(key, (size, size), jnp.float32).astype(dtype), device
    )

    @partial(jax.jit, static_argnums=(2,))
    def chain(x, w, n):
        def step(_, v):
            # normalize cheaply to keep values finite; fuses into the matmul
            y = jnp.dot(v, w, preferred_element_type=jnp.float32)
            return (y * (1.0 / size)).astype(dtype)
        out = jax.lax.fori_loop(0, n, step, x)
        return out.astype(jnp.float32).sum()  # scalar readback proves completion

    def run(n: int) -> float:
        return float(chain(a, w, n))  # float() forces host fetch

    lo, hi = max(iters, 1), max(iters, 1) * 4
    run(lo)
    run(hi)  # warm both compilations before any timing
    flops = 2.0 * size * size * size
    draws = []
    for _ in range(trials):
        t0 = time.perf_counter()
        run(lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(hi)
        t_hi = time.perf_counter() - t0
        dt = max((t_hi - t_lo) / (hi - lo), 1e-9)
        draws.append(flops / dt / 1e12)
    est = median(draws)
    return MatmulResult(
        size=size, dtype=jnp.dtype(dtype).name, iters=iters,
        time_s=flops / est / 1e12, tflops=_round_tflops(est),
        tflops_band=(_round_tflops(min(draws)), _round_tflops(max(draws))),
        trials=tuple(_round_tflops(d) for d in draws),
    )
