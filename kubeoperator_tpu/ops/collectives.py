"""Collective bus-bandwidth benchmarks over an explicit device mesh.

TPU-first design: where NCCL-tests spins up one process per GPU and
bootstraps rings at runtime, here the topology is declared (a
`jax.sharding.Mesh` from the plan's SliceTopology), the collective is a
one-line `shard_map` body, and XLA lowers it onto the physical ICI rings.
Bus-bandwidth formulas follow the nccl-tests conventions so numbers are
directly comparable with the GPU baseline being replaced:

    all_reduce      busbw = 2 * (n-1)/n * S / t
    all_gather      busbw =     (n-1)   * S / t   (output = n*S per device)
    reduce_scatter  busbw =     (n-1)/n * S / t
    all_to_all      busbw =     (n-1)/n * S / t
    ppermute (ring) busbw =               S / t

with S = the per-device shard bytes this harness allocates. Iterations run inside one jit'd
`lax.fori_loop` so dispatch overhead never pollutes the measurement
(XLA semantics: trace once, compile once, loop on device).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeoperator_tpu.ops.timing import differential_time_per_iter
from kubeoperator_tpu.parallel.mesh import flat_axis_mesh, shard_map_compat

AXIS = "devices"


@dataclass(frozen=True)
class CollectiveResult:
    op: str
    n_devices: int
    bytes_per_device: int
    time_per_iter_s: float
    algbw_gbps: float   # S / t
    busbw_gbps: float   # hardware-bus normalized (formulas above)

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def _bus_factor(op: str, n: int) -> float:
    if n <= 1:
        return 1.0
    if op == "psum":
        return 2.0 * (n - 1) / n
    if op == "all_gather":
        return float(n - 1)  # each device receives (n-1) remote shards of S
    if op in ("reduce_scatter", "all_to_all"):
        return (n - 1) / n
    return 1.0  # ppermute


def _collective_fn(op: str, mesh):
    """Build a jit'd `run(x, n)` executing n chained collectives on device."""
    n = mesh.devices.size

    if op == "psum":
        def body(x):
            # divide to keep magnitude stable across iterations; the divide
            # fuses into the all-reduce epilogue and is bandwidth-free.
            return jax.lax.psum(x, AXIS) / n
    elif op == "all_gather":
        def body(x):
            g = jax.lax.all_gather(x, AXIS, tiled=True)       # [n*m]
            return jax.lax.dynamic_slice_in_dim(
                g, jax.lax.axis_index(AXIS) * x.shape[0], x.shape[0]
            )
    elif op == "reduce_scatter":
        def body(x):
            s = jax.lax.psum_scatter(x, AXIS, tiled=True) / n  # [m/n]
            return jnp.tile(s, n)  # local re-expand so iterations chain
    elif op == "all_to_all":
        def body(x):
            y = x.reshape(n, -1)
            z = jax.lax.all_to_all(y, AXIS, split_axis=0, concat_axis=0,
                                   tiled=False)
            return z.reshape(x.shape)
    elif op == "ppermute":
        def body(x):
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, AXIS, perm)
    else:
        raise ValueError(f"unknown collective {op!r}")

    @partial(jax.jit, static_argnums=(1,))
    def run_iters(x, n):
        @partial(shard_map_compat, mesh=mesh, in_specs=P(AXIS),
                 out_specs=P(AXIS))
        def shard_body(v):
            def step(_, u):
                return body(u)
            return jax.lax.fori_loop(0, n, step, v)

        # scalar readback: proves remote completion without paying a full
        # array transfer (ops/timing.py rationale)
        return shard_body(x).sum()

    return run_iters


def bench_collective(
    op: str = "psum",
    size_mb: float = 16.0,
    mesh=None,
    iters: int = 10,
    trials: int = 3,
    dtype=jnp.float32,
) -> CollectiveResult:
    """Measure one collective's sustained bus bandwidth. `iters` is the high
    iteration count of the differential measurement; `trials` the number of
    timed rounds (median taken). Warmup is handled inside the timer."""
    mesh = mesh or flat_axis_mesh(AXIS)
    n = int(mesh.devices.size)
    elem = jnp.dtype(dtype).itemsize
    per_dev = max(int(size_mb * 1e6) // elem, 128)
    if op in ("all_to_all", "reduce_scatter"):
        per_dev = max(per_dev // n * n, n)  # shard must divide by n
    global_shape = (per_dev * n,)
    x = jax.device_put(
        jnp.ones(global_shape, dtype),
        NamedSharding(mesh, P(AXIS)),
    )
    fn = _collective_fn(op, mesh)

    def run(n: int) -> float:
        return float(fn(x, n))

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2),
        trials=max(trials, 1),
    )
    bytes_per_dev = per_dev * elem
    algbw = bytes_per_dev / dt / 1e9
    busbw = algbw * _bus_factor(op, n)
    return CollectiveResult(
        op=op, n_devices=n, bytes_per_device=bytes_per_dev,
        time_per_iter_s=dt, algbw_gbps=algbw, busbw_gbps=busbw,
    )


def verify_psum_correctness(mesh=None) -> bool:
    """All-reduce of per-device rank vectors must equal sum(0..n-1)."""
    mesh = mesh or flat_axis_mesh(AXIS)
    n = int(mesh.devices.size)

    @partial(shard_map_compat, mesh=mesh, in_specs=P(AXIS),
             out_specs=P(AXIS))
    def ranks_sum(x):
        mine = jnp.full_like(x, jax.lax.axis_index(AXIS), dtype=jnp.float32)
        return jax.lax.psum(mine, AXIS)

    out = np.asarray(jax.jit(ranks_sum)(jnp.zeros((n * 8,), jnp.float32)))
    expected = n * (n - 1) / 2
    return bool(np.all(out == expected))


def run_collective_suite(
    ops: tuple[str, ...] = ("psum", "all_gather", "reduce_scatter", "ppermute"),
    sizes_mb: tuple[float, ...] = (1.0, 8.0, 32.0),
    mesh=None,
    iters: int = 10,
) -> list[CollectiveResult]:
    """NCCL-tests-style sweep: every op at every size."""
    mesh = mesh or flat_axis_mesh(AXIS)
    results = []
    for op in ops:
        for size in sizes_mb:
            results.append(
                bench_collective(op, size_mb=size, mesh=mesh, iters=iters)
            )
    return results
