"""2-slice DCN psum smoke over real OS processes — the multislice smoke
gate (ROADMAP item 4, ISSUE 10 satellite 1).

The MULTICHIP matrix stopped at 8-device single-slice meshes; this module
is the gate that proves the MULTISLICE bootstrap end to end with TWO
processes per slice, so both boundary classes exist in one run:

  * the DCN (slice) boundary — the `dcn` mesh axis falls exactly on the
    slice_id the env contract assigned, and a psum over it crosses slices;
  * the intra-slice host boundary — each slice spans two OS processes, so
    an `ici_0` psum crosses processes WITHOUT crossing slices.

Workers are pure-CPU JAX runtimes wired through the SAME env contract the
JobSet templates in (`parallel.multislice.host_envs` → per-rank
`initialize_from_env`, gloo collectives on CPU), i.e. the exact bootstrap
a real multislice JobSet ships — only libtpu's DCN transport is folded
away. Consumed by tests/test_distributed.py (the tier-1 gate) and
`perf_matrix.py --multislice` (the committed PERF row).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

from kubeoperator_tpu.parallel.multislice import host_envs
from kubeoperator_tpu.parallel.topology import parse_accelerator_type

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RESULT_MARKER = "KO_TPU_DCN_SMOKE"

# Per-rank worker: bootstrap from the env contract FIRST, build the
# (dcn, ici_0) mesh from the declared geometry, assert the dcn axis lands
# on the slice boundary, then prove one cross-slice and one cross-host
# collective. Geometry arrives via env (KO_TPU_SMOKE_*) so the same source
# serves any slices × procs-per-slice shape.
WORKER_SRC = """
import json, os
slice_id = int(os.environ["KO_TPU_SLICE_ID"])
num_slices = int(os.environ["KO_TPU_SMOKE_SLICES"])
procs_per_slice = int(os.environ["KO_TPU_SMOKE_PROCS_PER_SLICE"])
local_devices = int(os.environ["KO_TPU_SMOKE_LOCAL_DEVICES"])
if num_slices > 1:
    assert os.environ["MEGASCALE_NUM_SLICES"] == str(num_slices)
    assert int(os.environ["MEGASCALE_SLICE_ID"]) == slice_id

from kubeoperator_tpu.parallel.multislice import initialize_from_env
initialize_from_env()
import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from kubeoperator_tpu.parallel.mesh import build_mesh, shard_map_compat

procs = num_slices * procs_per_slice
per_slice = procs_per_slice * local_devices
assert jax.process_count() == procs, jax.process_count()
assert jax.device_count() == procs * local_devices, jax.device_count()

# devices are process-major, so reshaping to (dcn, ici_0) puts each
# slice's processes in one dcn row — assert it rather than assume it
mesh = build_mesh(("dcn", "ici_0"), (num_slices, per_slice))
local = set(jax.local_devices())
for dcn_idx in range(num_slices):
    for dev in mesh.devices[dcn_idx].flat:
        if dev in local:
            assert dcn_idx == slice_id, (dcn_idx, slice_id)

# cross-slice: slice s contributes s+1 -> sum(1..N) everywhere
arr_d = jax.make_array_from_callback(
    (num_slices,), NamedSharding(mesh, P("dcn")),
    lambda idx: np.full((1,), float(slice_id + 1), np.float32))
dcn_sum = jax.jit(shard_map_compat(
    lambda a: jax.lax.psum(a, "dcn"), mesh, in_specs=P("dcn"),
    out_specs=P()))(arr_d)

# cross-host inside the slice: ici_0 position i contributes i+1; the
# axis spans this slice's TWO processes, so the psum crosses a process
# boundary without crossing the slice boundary
arr_h = jax.make_array_from_callback(
    (per_slice,), NamedSharding(mesh, P("ici_0")),
    lambda idx: np.full((1,), float(idx[0].start + 1), np.float32))
ici_sum = jax.jit(shard_map_compat(
    lambda a: jax.lax.psum(a, "ici_0"), mesh, in_specs=P("ici_0"),
    out_specs=P()))(arr_h)

print("{marker} " + json.dumps({
    "rank": jax.process_index(),
    "slice": slice_id,
    "dcn_psum": float(np.asarray(dcn_sum)[0]),
    "ici_psum": float(np.asarray(ici_sum)[0]),
}), flush=True)
""".replace("{marker}", RESULT_MARKER)


def _free_port_pair() -> int:
    """A port whose SUCCESSOR is also free: the multislice env contract
    hands out port for jax.distributed and port+1 for the megascale
    coordinator, so both must be bindable (and 65535 — whose successor
    cannot exist — must never be returned)."""
    for _attempt in range(32):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            if port >= 65535:
                continue
            with socket.socket() as s2:
                try:
                    s2.bind(("127.0.0.1", port + 1))
                except OSError:
                    continue
                return port
    raise RuntimeError("no free adjacent port pair found for the "
                       "multislice coordinator contract")


def _worker_env(base_env: dict, extra: dict, local_devices: int) -> dict:
    """Pure-CPU env for one worker: scrub the image's TPU-tunnel plumbing
    (its sitecustomize registers a remote backend whenever those are set),
    force the virtual CPU device count, and put the repo on PYTHONPATH."""
    env = {
        k: v for k, v in base_env.items()
        if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "MEGASCALE"))
        and k != "XLA_FLAGS"
    }
    env.update(extra)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={local_devices}")
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def run_dcn_smoke(tpu_type: str = "v5p-16", num_slices: int = 2,
                  local_devices: int = 2, timeout_s: float = 300.0) -> dict:
    """Run the multislice smoke gate: one OS process per host of
    `tpu_type` × `num_slices` (v5p-16 ⇒ 2 hosts/slice ⇒ two processes
    per slice), each a pure-CPU JAX runtime bootstrapped from the
    host_envs contract. Returns the machine report (`ok`, per-boundary
    psum values, wall time) the test gate and the PERF row both consume."""
    topo = parse_accelerator_type(tpu_type, num_slices=num_slices)
    envs = host_envs(topo, "127.0.0.1", port=_free_port_pair())
    procs_per_slice = topo.hosts_per_slice
    expected_dcn = float(sum(range(1, num_slices + 1)))
    per_slice = procs_per_slice * local_devices
    expected_ici = float(sum(range(1, per_slice + 1)))

    t0 = time.monotonic()
    workers = []
    for henv in envs:
        extra = dict(henv.to_env())
        extra.update({
            "KO_TPU_SMOKE_SLICES": str(num_slices),
            "KO_TPU_SMOKE_PROCS_PER_SLICE": str(procs_per_slice),
            "KO_TPU_SMOKE_LOCAL_DEVICES": str(local_devices),
        })
        workers.append(subprocess.Popen(  # KO-P006: waived — communicate(timeout=) below bounds every worker, and the finally block kills stragglers
            [sys.executable, "-c", WORKER_SRC],
            env=_worker_env(dict(os.environ), extra, local_devices),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    results, errors = [], []
    try:
        for proc in workers:
            out, err = proc.communicate(timeout=timeout_s)
            if proc.returncode != 0:
                errors.append(err[-2000:])
                continue
            for line in out.splitlines():
                if line.startswith(RESULT_MARKER):
                    results.append(json.loads(line[len(RESULT_MARKER):]))
    finally:
        for proc in workers:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    wall_s = time.monotonic() - t0
    ok = (
        not errors
        and len(results) == len(envs)
        and all(r["dcn_psum"] == expected_dcn for r in results)
        and all(r["ici_psum"] == expected_ici for r in results)
    )
    return {
        "ok": ok,
        "tpu_type": tpu_type,
        "num_slices": num_slices,
        "processes": len(envs),
        "procs_per_slice": procs_per_slice,
        "local_devices": local_devices,
        "global_devices": len(envs) * local_devices,
        "dcn_psum": sorted({r["dcn_psum"] for r in results}),
        "ici_psum": sorted({r["ici_psum"] for r in results}),
        "expected_dcn_psum": expected_dcn,
        "expected_ici_psum": expected_ici,
        "errors": errors,
        "wall_s": round(wall_s, 3),
    }


def main() -> int:
    report = run_dcn_smoke()
    print(RESULT_MARKER + "_REPORT " + json.dumps(report), flush=True)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
