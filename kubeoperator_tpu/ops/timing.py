"""Honest device timing over asymmetric transports.

Two realities this framework measures under:
  * real TPU behind a relay/tunnel: dispatch+readback RTT can dwarf device
    time, and `block_until_ready` may complete before remote execution does —
    only a host readback proves completion;
  * CI CPU meshes: RTT ~ 0, classic timing works.

The one method correct in both: reduce the result to a scalar ON DEVICE
(4-byte readback), and time the same computation at two iteration counts —
the RTT cancels in the difference:

    t_per_iter = (t(hi) - t(lo)) / (hi - lo)

Runs `trials` rounds and takes the median delta for noise robustness.
"""

from __future__ import annotations

import time
from statistics import median
from typing import Callable


def differential_time_per_iter(
    run: Callable[[int], object],
    lo: int,
    hi: int,
    trials: int = 3,
) -> float:
    """`run(iters)` must execute iters chained device iterations and block on
    a scalar readback. Returns seconds per iteration (>= 1ns)."""
    if hi <= lo:
        raise ValueError("hi must exceed lo")
    # warm both compilations before any timing
    run(lo)
    run(hi)
    deltas = []
    for _ in range(trials):
        t0 = time.perf_counter()
        run(lo)
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(hi)
        t_hi = time.perf_counter() - t0
        deltas.append((t_hi - t_lo) / (hi - lo))
    return max(median(deltas), 1e-9)
