"""The cluster smoke test — gates Ready on TPU plans (BASELINE metrics).

Runs on every host of the slice (one process per host, launched by the
tpu-smoke-test role's Job/JobSet): bootstrap `jax.distributed` from the env
contract, verify the expected chip count is visible, check psum correctness,
sweep psum bus-bandwidth, and emit the one-line machine-readable result the
adm post-hook parses:

    KO_TPU_SMOKE_RESULT {"gbps": ..., "chips": ..., "ok": true, ...}

Exit code 0 only if correctness holds and the chip count matches — bandwidth
thresholds are enforced server-side (ClusterSpec.smoke_test_gbps_threshold)
so policy changes don't need an image rebuild.
"""

from __future__ import annotations

import json
import os
import sys

from kubeoperator_tpu.parallel.multislice import initialize_from_env


def run_smoke(
    sizes_mb: tuple[float, ...] = (1.0, 8.0, 32.0, 64.0),
    iters: int = 10,
) -> dict:
    import jax

    from kubeoperator_tpu.ops.collectives import (
        bench_collective,
        verify_psum_correctness,
    )
    from kubeoperator_tpu.parallel.mesh import flat_axis_mesh

    chips = jax.device_count()
    expected = int(os.environ.get("KO_TPU_EXPECTED_CHIPS", "0"))
    mesh = flat_axis_mesh()
    ok = verify_psum_correctness(mesh)

    # long-context path: exact ring attention over the same mesh axis —
    # catches ICI permute-ordering/mask bugs raw psum can't see
    ring_ok = True
    if chips >= 2:
        from kubeoperator_tpu.ops.longcontext_check import (
            verify_ring_attention,
        )

        ring_ok = verify_ring_attention(flat_axis_mesh("sp"))

    best = 0.0
    table = []
    for size in sizes_mb:
        r = bench_collective("psum", size_mb=size, mesh=mesh, iters=iters)
        table.append({"size_mb": size, "busbw_gbps": round(r.busbw_gbps, 3)})
        best = max(best, r.busbw_gbps)

    result = {
        "gbps": round(best, 3),
        "chips": chips,
        "ok": bool(ok) and bool(ring_ok)
              and (expected == 0 or chips == expected),
        "correctness": bool(ok),
        "ring_attention_correct": bool(ring_ok),
        "expected_chips": expected,
        "process_index": jax.process_index(),
        "num_processes": jax.process_count(),
        "table": table,
    }

    # optional deeper gate (smoke_train_steps var -> KO_TPU_TRAIN_STEPS):
    # a few real sharded training steps of the validation net; loss must
    # be finite and descending on the actual slice
    train_steps = int(os.environ.get("KO_TPU_TRAIN_STEPS", "0"))
    if train_steps > 0:
        from kubeoperator_tpu.ops.train_smoke import run_train_smoke

        train = run_train_smoke(steps=train_steps)
        result["train"] = train
        result["ok"] = bool(result["ok"]) and bool(train["ok"])
    return result


def main() -> int:
    initialize_from_env()
    import jax

    result = run_smoke()
    # every process validates; only process 0 speaks (its pod's logs are what
    # the tpu-smoke-test role collects)
    if jax.process_index() == 0:
        print("KO_TPU_SMOKE_RESULT " + json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
