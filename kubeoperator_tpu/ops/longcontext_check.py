"""Long-context validation workload — ring attention as a slice health check.

The collective suite (collectives.py) proves raw ICI bandwidth; this module
proves the *composed* long-context path the framework ships
(parallel/longcontext.py): exact causal ring attention with K/V rotating
over a mesh axis. It joins the smoke/diag family (SURVEY.md §5.7's
long-context analog) as:

* ``verify_ring_attention``  — sharded result must match single-device full
  attention bit-for-tolerance; any ICI permute ordering bug, stale-block
  reuse, or mask off-by-one fails it.
* ``bench_ring_attention``   — sustained attention TFLOP/s over the ring,
  differential-timed like every other workload (ops/timing.py rationale).

Like the rest of ops/, runs on CPU meshes for CI and real slices for the
metric runs.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from kubeoperator_tpu.ops.timing import differential_time_per_iter
from kubeoperator_tpu.parallel.longcontext import (
    reference_attention,
    ring_attention,
    ring_attention_local,
)
from kubeoperator_tpu.parallel.mesh import (
    axis_size,
    flat_axis_mesh,
    shard_map_compat,
)

AXIS = "sp"


def verify_ring_attention(mesh=None, causal: bool = True,
                          tol: float = 2e-4) -> bool:
    """Exactness gate: ring attention over the mesh vs reference attention
    on the gathered arrays. Small f32 shapes — this is a correctness probe,
    not a throughput number."""
    mesh = mesh or flat_axis_mesh(AXIS)
    n = axis_size(mesh, AXIS)
    b, s_local, h, dh = 2, 8, 4, 16
    rng = np.random.default_rng(0)
    shape = (b, s_local * n, h, dh)
    q_h, k_h, v_h = (rng.standard_normal(shape).astype(np.float32)
                     for _ in range(3))
    spec = P(None, AXIS, None, None)
    q, k, v = (jax.device_put(a, NamedSharding(mesh, spec))
               for a in (q_h, k_h, v_h))
    out = np.asarray(jax.device_get(
        ring_attention(q, k, v, mesh, axis_name=AXIS, causal=causal)))
    want = np.asarray(reference_attention(
        jnp.asarray(q_h), jnp.asarray(k_h), jnp.asarray(v_h), causal=causal))
    return bool(np.allclose(out, want, rtol=tol, atol=tol))


@dataclass(frozen=True)
class RingAttentionResult:
    n_devices: int
    seq_global: int
    heads: int
    head_dim: int
    causal: bool
    time_per_iter_s: float
    tflops: float

    def to_dict(self) -> dict:
        return {
            "n_devices": self.n_devices,
            "seq_global": self.seq_global,
            "heads": self.heads,
            "head_dim": self.head_dim,
            "causal": self.causal,
            "time_per_iter_s": round(self.time_per_iter_s, 6),
            "tflops": round(self.tflops, 3),
        }


def bench_ring_attention(
    seq_per_device: int = 512,
    heads: int = 8,
    head_dim: int = 64,
    batch: int = 1,
    causal: bool = True,
    mesh=None,
    iters: int = 8,
    trials: int = 3,
    dtype=jnp.bfloat16,
) -> RingAttentionResult:
    """Sustained ring-attention throughput. FLOP count is the standard
    4·b·h·dh·s² (QKᵀ + PV, both 2·…); the causal variant computes the full
    score block and masks, so the count is not halved."""
    mesh = mesh or flat_axis_mesh(AXIS)
    n = axis_size(mesh, AXIS)
    s_global = seq_per_device * n
    rng = np.random.default_rng(0)
    shape = (batch, s_global, heads, head_dim)
    spec = P(None, AXIS, None, None)
    q, k, v = (
        jax.device_put(
            rng.standard_normal(shape).astype(np.float32).astype(dtype),
            NamedSharding(mesh, spec))
        for _ in range(3)
    )

    @partial(jax.jit, static_argnums=(3,))
    def run_iters(qq, kk, vv, m):
        def shard_body(qb, kb, vb):
            def step(_, u):
                return ring_attention_local(u, kb, vb, AXIS, n, causal)
            return jax.lax.fori_loop(0, m, step, qb)

        out = shard_map_compat(
            shard_body, mesh, in_specs=(spec, spec, spec), out_specs=spec,
        )(qq, kk, vv)
        # scalar readback proves remote completion (ops/timing.py rationale)
        return out.astype(jnp.float32).sum()

    def run(m: int) -> float:
        return float(run_iters(q, k, v, m))

    dt = differential_time_per_iter(
        run, lo=max(iters // 4, 1), hi=max(iters, iters // 4 + 2),
        trials=max(trials, 1),
    )
    flops = 4.0 * batch * heads * head_dim * float(s_global) ** 2
    return RingAttentionResult(
        n_devices=n, seq_global=s_global, heads=heads, head_dim=head_dim,
        causal=causal, time_per_iter_s=dt, tflops=flops / dt / 1e12,
    )
