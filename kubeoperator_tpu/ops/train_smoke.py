"""Training smoke: a few REAL sharded training steps as a slice health
workload.

The psum smoke proves the interconnect moves bytes; this proves the whole
TPU training loop — MXU matmuls, ring-attention collectives, MoE
all-to-all, pipeline ppermute, backward pass, remat, SGD update —
compiles and RUNS on the actual slice, using the same validation net the
driver's multichip dryrun exercises (parallel/validation_net.py). Pass
criteria: every loss finite, and the loss after the last step is below
the first (a tiny net on a fixed batch must descend; a slice with a sick
chip or a miswired ICI ring either diverges, NaNs, or hangs).

Emits the same one-line machine contract style as psum_smoke:

    KO_TPU_TRAIN_RESULT {"ok": true, "losses": [...], "steps_per_s": ...}
"""

from __future__ import annotations

import json
import sys
import time

from kubeoperator_tpu.parallel.multislice import initialize_from_env


def run_train_smoke(
    steps: int = 4,
    devices=None,
    peak_tflops_per_chip: float | None = None,
    cfg=None,
) -> dict:
    import jax

    from kubeoperator_tpu.parallel import validation_net as vnet

    cfg = cfg or vnet.NetConfig()
    devices = list(devices) if devices is not None else list(jax.devices())
    mesh = vnet.build_mesh_for(devices)
    params, x, _ = vnet.build_params_and_batch(mesh, cfg=cfg)
    train_step = vnet.make_train_step(mesh, cfg=cfg)

    # compile outside the timed window; this is also step 1 of `steps`
    loss, params = train_step(params, x)
    device_losses = [loss]
    float(jax.device_get(loss))                   # warm-up fence
    float(jax.device_get(params["w_head"][0, 0]))  # compile the end fence too
    t0 = time.perf_counter()
    for _ in range(max(steps - 1, 0)):
        loss, params = train_step(params, x)
        device_losses.append(loss)
    # fence ONCE at the end via a value transfer that depends on the LAST
    # step's parameter UPDATE (not just its loss — the loss only proves the
    # forward pass ran): steps dispatch asynchronously and pipeline on
    # device, so a tunneled/remote runtime's per-call RTT doesn't
    # masquerade as step time — and unlike block_until_ready (which the
    # experimental axon backend acks early), a scalar fetch cannot
    # complete before the compute it depends on has.
    float(jax.device_get(params["w_head"][0, 0]))
    dt = time.perf_counter() - t0
    losses = [float(jax.device_get(l)) for l in device_losses]

    finite = all(l == l and abs(l) != float("inf") for l in losses)
    # a single-step run has no loss pair to compare — finiteness is the gate
    descending = losses[-1] < losses[0] if len(losses) > 1 else True
    ok = finite and descending
    steps_per_s = round((len(losses) - 1) / dt, 3) if dt > 0 else 0.0
    # steps/s is config-relative; convert to achieved model TFLOP/s (and
    # MFU when the caller supplies the generation's datasheet peak) so the
    # bench line carries a comparable efficiency number (VERDICT r2 #9)
    step_flops = vnet.analytic_train_flops(mesh, cfg)
    tflops_per_s = round(steps_per_s * step_flops / 1e12, 4)
    result = {
        "ok": ok,
        "finite": finite,
        "descending": descending,
        "losses": [round(l, 6) for l in losses],
        "steps_per_s": steps_per_s,
        "model_tflops_per_s": tflops_per_s,
        "devices": len(devices),
        "mesh": {a: int(mesh.shape[a]) for a in mesh.axis_names},
    }
    if peak_tflops_per_chip:
        peak = peak_tflops_per_chip * len(devices)
        result["mfu_pct"] = round(100.0 * tflops_per_s / peak, 3)
    return result


def main() -> int:
    """Job/JobSet entrypoint (mirrors psum_smoke.main): bootstrap
    jax.distributed from the env contract, run, emit the marker line."""
    initialize_from_env()
    result = run_train_smoke()
    print("KO_TPU_TRAIN_RESULT " + json.dumps(result), flush=True)
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
