"""HBM stream bandwidth via an XLA-fused triad.

Single-chip memory-health probe (o = a + s*b streams 3 buffers through HBM;
STREAM-triad convention). The triad is a *fused XLA elementwise kernel* on
purpose: measured on a real v5e chip, XLA's fusion sustains ~688 GB/s (84%
of the 819 GB/s datasheet) while a hand-written pallas triad — swept over
(8,128)-aligned block sizes 256/512 rows × 1024 lanes, with the bounding
scale folded in — plateaus at ~404 GB/s because `pallas_call`'s automatic
double-buffered pipeline cannot overlap the three streams as aggressively
as XLA's fused loop. Streaming elementwise is exactly what the guide says
to leave to the compiler ("let XLA fuse — don't hand-schedule what the
compiler already does"); manual-DMA peak bandwidth is reported separately
by ops/pallas_kernels.py::dma_read_bandwidth_gbps (~735 GB/s, 90%).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubeoperator_tpu.ops.timing import differential_time_per_iter

COLS = 1024  # lane-aligned (multiple of 128)


@dataclass(frozen=True)
class HbmResult:
    bytes_streamed: int
    time_s: float
    gbps: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def hbm_bandwidth_gbps(
    size_mb: float = 256.0, iters: int = 10, device: jax.Device | None = None
) -> HbmResult:
    """Sustained triad bandwidth; on CPU a tiny run (CI only)."""
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        size_mb = min(size_mb, 8.0)  # CPU CI: keep it fast
        iters = min(iters, 4)
    elem = 4
    rows = max(int(size_mb * 1e6) // (COLS * elem), 8)
    x = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)
    y = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)

    @partial(jax.jit, static_argnums=(2,))
    def chain(a, b, n):
        def step(_, v):
            # scale keeps values bounded; XLA fuses the whole expression
            # into one three-stream pass over HBM
            return (v + 2.5 * b) * 0.5
        out = jax.lax.fori_loop(0, n, step, a)
        return out.sum()  # scalar readback (ops/timing.py rationale)

    def run(n: int) -> float:
        return float(chain(x, y, n))

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2)
    )
    bytes_streamed = 3 * rows * COLS * elem  # read a, read b, write o
    return HbmResult(
        bytes_streamed=bytes_streamed, time_s=dt,
        gbps=bytes_streamed / dt / 1e9,
    )
