"""HBM stream bandwidth via an XLA-fused triad.

Single-chip memory-health probe (o = a + s*b streams 3 buffers through HBM;
STREAM-triad convention). The triad is a *fused XLA elementwise kernel* on
purpose: measured on a real v5e chip, XLA's fusion sustains ~688 GB/s (84%
of the 819 GB/s datasheet) while a hand-written pallas triad — swept over
(8,128)-aligned block sizes 256/512 rows × 1024 lanes, with the bounding
scale folded in — plateaus at ~404 GB/s because `pallas_call`'s automatic
double-buffered pipeline cannot overlap the three streams as aggressively
as XLA's fused loop. Streaming elementwise is exactly what the guide says
to leave to the compiler ("let XLA fuse — don't hand-schedule what the
compiler already does"); manual-DMA peak bandwidth is reported separately
by ops/pallas_kernels.py::dma_read_bandwidth_gbps (~735-761 GB/s, 90-93%).

MEASURED CEILING ANALYSIS (r4 sweep, real v5e behind the axon tunnel —
the VERDICT r3 #4 knee investigation; all long-loop differential timing,
hi=40, trials=5, values in GB/s):

* size sweep (f32, cols=1024): 256MB buffers sit on the plateau; 512MB
  → 619, 1024MB → 550 (sustained decline at large working sets — refresh/
  page pressure). Below ~128MB per buffer the number INFLATES past the
  819 datasheet (821-1095 observed, physically impossible): v5e's large
  VMEM lets XLA keep part of the working set on-chip, so small-buffer
  runs are not HBM measurements at all. 256MB/buffer (768MB traffic per
  iteration) is the smallest size that provably streams.
* layout sweep (cols 512/1024/2048/4096/8192 at 256MB): short-loop runs
  suggested cols=4096 wins (751); long-loop repeats collapse the spread —
  672-722 across ALL layouts with ±4% run-to-run tunnel variance. Layout
  is not a lever here.
* dtype: bf16 triad is WORSE (611-639) — halving element size doubles
  element count for the same bytes and the VPU-side loop, not HBM,
  becomes the limiter.
* stream decomposition (same buffers, same timing): read-only 623-651,
  write-only 536-624, copy 1R1W 667-710, triad 2R1W 650-682. Mixed
  read+write traffic BEATS either pure direction — the HBM controller
  overlaps directions — so no access-mix rebalancing can lift the triad:
  copy, the best mix, peaks ~710.

Conclusion: ~670-720 sustained (82-88% of datasheet) IS the fused-XLA
3-stream ceiling on this part; the 761 GB/s manual-DMA read shows the
remaining headroom belongs to read-dominated manual pipelines, not to any
triad. The bench reports best-of-2 with the spread so tunnel variance is
visible instead of reading as progress/regression.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from kubeoperator_tpu.ops.timing import differential_time_per_iter

COLS = 1024  # lane-aligned (multiple of 128)


@dataclass(frozen=True)
class HbmResult:
    bytes_streamed: int
    time_s: float
    gbps: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def hbm_bandwidth_gbps(
    size_mb: float = 256.0, iters: int = 10, device: jax.Device | None = None
) -> HbmResult:
    """Sustained triad bandwidth; on CPU a tiny run (CI only)."""
    device = device or jax.devices()[0]
    if device.platform != "tpu":
        size_mb = min(size_mb, 8.0)  # CPU CI: keep it fast
        iters = min(iters, 4)
    elem = 4
    rows = max(int(size_mb * 1e6) // (COLS * elem), 8)
    x = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)
    y = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)

    @partial(jax.jit, static_argnums=(2,))
    def chain(a, b, n):
        def step(_, v):
            # scale keeps values bounded; XLA fuses the whole expression
            # into one three-stream pass over HBM
            return (v + 2.5 * b) * 0.5
        out = jax.lax.fori_loop(0, n, step, a)
        return out.sum()  # scalar readback (ops/timing.py rationale)

    def run(n: int) -> float:
        return float(chain(x, y, n))

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2)
    )
    bytes_streamed = 3 * rows * COLS * elem  # read a, read b, write o
    return HbmResult(
        bytes_streamed=bytes_streamed, time_s=dt,
        gbps=bytes_streamed / dt / 1e9,
    )
