"""HBM stream bandwidth via a pallas triad kernel.

Single-chip memory-health probe (o = a + s*b streams 3 buffers through HBM;
STREAM-triad convention). The kernel is a real pallas TPU kernel — VMEM
blocks aligned to the (8,128) f32 tile, 1-D grid over row blocks — with
`interpret=True` on CPU so CI exercises the same code path
(/opt/skills/guides/pallas_guide.md patterns).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kubeoperator_tpu.ops.timing import differential_time_per_iter

BLOCK_ROWS = 256
COLS = 1024  # lane-aligned (multiple of 128)


def _triad_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = a_ref[...] + 2.5 * b_ref[...]


@dataclass(frozen=True)
class HbmResult:
    bytes_streamed: int
    time_s: float
    gbps: float

    def to_dict(self) -> dict:
        return dict(self.__dict__)


def _triad(x, y, interpret: bool):
    rows = x.shape[0]
    return pl.pallas_call(
        _triad_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        grid=(rows // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((BLOCK_ROWS, COLS), lambda i: (i, 0)),
            pl.BlockSpec((BLOCK_ROWS, COLS), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, COLS), lambda i: (i, 0)),
        interpret=interpret,
    )(x, y)


def hbm_bandwidth_gbps(
    size_mb: float = 256.0, iters: int = 10, device: jax.Device | None = None
) -> HbmResult:
    """Sustained triad bandwidth; on CPU a tiny interpreted run (CI only)."""
    device = device or jax.devices()[0]
    interpret = device.platform != "tpu"
    if interpret:
        size_mb = min(size_mb, 2.0)  # interpreter is slow; keep CI fast
        iters = min(iters, 2)
    elem = 4
    rows = max(int(size_mb * 1e6) // (COLS * elem) // BLOCK_ROWS, 1) * BLOCK_ROWS
    x = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)
    y = jax.device_put(jnp.ones((rows, COLS), jnp.float32), device)

    from functools import partial

    @partial(jax.jit, static_argnums=(2,))
    def chain(a, b, n):
        def step(_, v):
            # scale keeps values bounded; the multiply rides the same stream
            return _triad(v, b, interpret) * 0.5
        out = jax.lax.fori_loop(0, n, step, a)
        return out.sum()  # scalar readback (ops/timing.py rationale)

    def run(n: int) -> float:
        return float(chain(x, y, n))

    dt = differential_time_per_iter(
        run, lo=max(iters // 8, 1), hi=max(iters, iters // 8 + 2)
    )
    bytes_streamed = 3 * rows * COLS * elem  # read a, read b, write o
    return HbmResult(
        bytes_streamed=bytes_streamed, time_s=dt,
        gbps=bytes_streamed / dt / 1e9,
    )
