"""TPU validation workloads — the NCCL-tests replacement family
(SURVEY.md §2.1 row 4a / §5.8).

Where the reference's GPU role deployed the prebuilt NCCL-tests CUDA binary
as its validation workload, this package ships pure-JAX/XLA workloads that
exercise the same hardware axes TPU-natively:

  collectives.py  ICI/DCN collective bus-bandwidth (psum, all_gather,
                  reduce_scatter, ppermute, all_to_all) over an explicit
                  jax.sharding.Mesh via shard_map
  matmul.py       MXU sustained bf16 throughput (systolic-array health)
  hbm.py          HBM stream bandwidth (pallas triad kernel)
  pallas_kernels.py  hand-scheduled diagnostics: double-buffered DMA read
                  stream + explicit remote-DMA ICI ring all-gather
  psum_smoke.py   the cluster smoke test: correctness + psum bus-bandwidth
                  across the full slice, emitting KO_TPU_SMOKE_RESULT
  longcontext_check.py  ring-attention exactness + throughput over the ICI
                  ring (the long-context path of parallel/longcontext.py)
  train_smoke.py  a few real sharded training steps of the validation net
                  (parallel/validation_net.py) — loss must descend

Everything here runs on CPU meshes for CI (virtual devices) and on real TPU
for the metric runs; no NCCL/MPI anywhere [BASELINE].
"""

from kubeoperator_tpu.ops.collectives import (
    CollectiveResult,
    bench_collective,
    run_collective_suite,
)
from kubeoperator_tpu.ops.matmul import mxu_matmul_tflops
from kubeoperator_tpu.ops.hbm import hbm_bandwidth_gbps
from kubeoperator_tpu.ops.pallas_kernels import (
    bench_ring_all_gather,
    dma_read_bandwidth_gbps,
    ring_all_gather,
    verify_ring_all_gather,
)
from kubeoperator_tpu.ops.longcontext_check import (
    RingAttentionResult,
    bench_ring_attention,
    verify_ring_attention,
)
from kubeoperator_tpu.ops.train_smoke import run_train_smoke

__all__ = [
    "CollectiveResult",
    "bench_collective",
    "run_collective_suite",
    "mxu_matmul_tflops",
    "hbm_bandwidth_gbps",
    "bench_ring_all_gather",
    "dma_read_bandwidth_gbps",
    "ring_all_gather",
    "verify_ring_all_gather",
    "RingAttentionResult",
    "bench_ring_attention",
    "verify_ring_attention",
    "run_train_smoke",
]
